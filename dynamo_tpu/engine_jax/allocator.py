"""Host-side paged KV block allocator with prefix caching and event emission.

Owns the mapping from logical sequences to physical pages of the device KV
pool. Full blocks are content-addressed by their chained sequence hash
(kv/tokens.py), so a new request whose prompt shares a block-aligned prefix
with a cached sequence reuses those pages and skips recomputing them.

Lifecycle of a physical block:
    free → active (refcount ≥ 1, owned by live sequences)
         → cached (refcount 0 but contents valid; reusable by hash, LRU-evictable)
         → free (evicted; `removed` event emitted)

Emits stored/removed events to a :class:`KvEventSink` — the same signal the
reference's engines publish for KV-aware routing (SURVEY.md §3.5); the radix
indexer consumes them. Capability parity with the reference's block reuse pool
(lib/llm/src/kv/reuse.rs, prefix_caching in the patched vLLM) — re-designed,
not ported: single-threaded host logic driven by the engine loop.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Protocol, Sequence, Tuple

from dynamo_tpu.kv.tokens import TokenBlockSequence, compute_block_hashes_for_seq


class KvDtypeMismatch(TypeError):
    """KV pages and the target pool disagree on the storage layout (int8
    pages+scales vs native dtype). Raised instead of writing mismatched
    bytes into the pool — a dtype skew must surface as a clean typed error,
    never as silently corrupt pages. The disagg transfer plane maps it to a
    prefill-failure reply so the decode side falls back to local prefill."""


class MigrationRejected(RuntimeError):
    """A target engine refused to stage a live-migrated stream (out of KV
    blocks, block-size/page-count mismatch, history longer than its
    max_model_len). Typed so the transfer plane's ``migrate`` op nacks
    cleanly and the source degrades that stream to the ordinary resume
    path — never a torn page set (docs/resilience.md §Live migration)."""


class KvEventSink(Protocol):
    """Receiver for KV cache events (worker → router)."""

    def blocks_stored(
        self, parent_hash: Optional[int], blocks: List[Tuple[int, List[int]]]
    ) -> None:
        """blocks: [(block_hash, token_ids), ...] in chain order."""

    def blocks_removed(self, block_hashes: List[int]) -> None: ...


@dataclass
class SequenceAllocation:
    """A live sequence's hold on physical pages."""

    block_ids: List[int]  # physical page ids, logical order
    token_blocks: TokenBlockSequence  # hashing state (tracks sealed blocks)
    cached_tokens: int  # prompt tokens served from prefix cache (any tier)
    sealed_blocks: int = 0  # how many full blocks have been hashed+registered
    # QoS attribution (runtime/qos.py): owning tenant + class level. The
    # allocator sums hard-held blocks per tenant (KV budgets) and tags
    # cached blocks with their owners' level so eviction under pressure
    # reclaims the lowest class first. Both stay at their defaults on the
    # single-tenant path — no per-tenant dict is ever touched.
    tenant: str = ""
    level: int = 0
    # host-tier prefix hits: (logical block index, sequence hash, k, v,
    # k_scale, v_scale, crc) with the content captured at probe time (a
    # later offload into the LRU pool can't invalidate them). The scale
    # entries are None for native-dtype pools and [L, bs] float32 tables
    # for int8 pools — scales travel WITH their pages through every tier;
    # ``crc`` is the seal-time content checksum (None with integrity off),
    # already VERIFIED at probe time. The engine must inject each into
    # block_ids[index] before any compute touches the sequence.
    host_hits: List[Tuple[int, int, Any, Any, Any, Any, Any]] = field(default_factory=list)
    # full-prompt block hashes this sequence advertised as in-flight (it will
    # compute + seal them); unregistered on free if still unsealed
    pending_hashes: List[int] = field(default_factory=list)


class InflightPrefix:
    """Returned by :meth:`BlockAllocator.allocate_sequence` when another live
    sequence is currently computing this prompt's next prefix block: the
    caller should keep the request pending and retry — once the owner seals
    the shared blocks they become ordinary prefix-cache hits, so the shared
    prefill is computed exactly once (reference: the reserved/shared in-flight
    block registry, lib/llm/src/kv/reserved.rs:23-127)."""

    __slots__ = ("seq_hash",)

    def __init__(self, seq_hash: int):
        self.seq_hash = seq_hash


class HostKvPool:
    """Host-RAM tier of the KV cache: evicted device blocks spill here.

    Content-addressed by the same chained sequence hash as the device tier,
    LRU-bounded. TPU analogue of the reference's pinned-host block pool
    (`lib/llm/src/kv/manager.rs:79-124`, `kv/storage.rs` CudaPinnedMemory):
    host arrays re-enter HBM via the engine's donated-scatter inject path.
    """

    def __init__(self, max_blocks: int):
        self.max_blocks = max_blocks
        # hash → (k, v, k_scale, v_scale, crc); scales are None for native-
        # dtype pools and per-token tables for int8 pools — the pool is
        # payload-agnostic so both layouts ride the same LRU. ``crc`` is the
        # block's seal-time content checksum (None with the integrity plane
        # off / from pre-integrity spills): verified at rehit so bad host
        # RAM surfaces as a prefix miss, never as corrupt device pages.
        self._data: "OrderedDict[int, Tuple[Any, Any, Any, Any, Any]]" = OrderedDict()
        self.hits = 0
        self.offloaded = 0

    def __contains__(self, h: int) -> bool:
        return h in self._data

    def __len__(self) -> int:
        return len(self._data)

    def put(self, h: int, k, v, k_scale=None, v_scale=None, crc=None) -> None:
        if h in self._data:
            self._data.move_to_end(h)
            return
        while len(self._data) >= self.max_blocks:
            self._data.popitem(last=False)
        self._data[h] = (k, v, k_scale, v_scale, crc)
        self.offloaded += 1

    def get(self, h: int) -> Optional[Tuple[Any, Any, Any, Any, Any]]:
        item = self._data.get(h)
        if item is not None:
            self._data.move_to_end(h)
            self.hits += 1
        return item

    def discard(self, h: int) -> None:
        """Drop a poisoned entry (failed its rehit checksum): it must never
        be served again — the prompt recomputes instead."""
        self._data.pop(h, None)


class _TieredLru:
    """The reclaimable-block reuse pool, tiered by QoS class level.

    Blocks land in the tier of their (highest) owning class; eviction
    pops the *lowest* tier first, LRU-oldest within a tier — so under KV
    pressure a batch tenant's warm cache is reclaimed before a premium
    tenant's (the reference framework's priority-aware reuse, re-designed
    for the paged pool). With QoS off every block lives in tier 0 and
    behavior is exactly the old single-OrderedDict LRU.
    """

    __slots__ = ("_tiers", "_tier_of", "_size")

    def __init__(self) -> None:
        self._tiers: Dict[int, "OrderedDict[int, None]"] = {}
        self._tier_of: Dict[int, int] = {}
        self._size = 0

    def __contains__(self, bid: int) -> bool:
        return bid in self._tier_of

    def __len__(self) -> int:
        return self._size

    def add(self, bid: int, level: int = 0) -> None:
        """Insert (or refresh) a block as most-recently-used in its tier."""
        old = self._tier_of.get(bid)
        if old is not None:
            od = self._tiers[old]
            del od[bid]
            self._size -= 1
        tier = self._tiers.setdefault(level, OrderedDict())
        tier[bid] = None  # fresh insert lands most-recently-used
        self._tier_of[bid] = level
        self._size += 1

    def discard(self, bid: int) -> bool:
        level = self._tier_of.pop(bid, None)
        if level is None:
            return False
        del self._tiers[level][bid]
        self._size -= 1
        return True

    def pop_oldest(self) -> Optional[int]:
        """Evict: lowest class level first, LRU-oldest within the level."""
        if self._size == 0:
            return None
        for level in sorted(self._tiers):
            od = self._tiers[level]
            if od:
                bid, _ = od.popitem(last=False)
                del self._tier_of[bid]
                self._size -= 1
                return bid
        return None


class BlockAllocator:
    """Allocates physical pages, reuses prefix-cached ones, evicts LRU
    (class-tiered when QoS levels flow — see :class:`_TieredLru`).

    All methods are called from the engine's step loop (single thread).
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_sink: Optional[KvEventSink] = None,
        salt: Optional[bytes] = None,
        host_pool: Optional[HostKvPool] = None,
        offload: Optional[Callable[[List[Tuple[int, int, Any]]], None]] = None,
        checksum: Optional[Callable[[List[int]], List[int]]] = None,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.salt = salt
        self._sink = event_sink
        # host tier: `offload([(hash, block_id), ...])` is called while the
        # evicted blocks' device contents are still valid; the engine copies
        # them into `host_pool` (device_get) before they can be overwritten
        self.host_pool = host_pool
        self._offload = offload
        # integrity plane (runtime/integrity.py, docs/resilience.md §Silent
        # corruption): ``checksum([block_ids]) -> [crc32]`` is the engine's
        # callback computing content checksums of freshly SEALED blocks
        # (the one point where the bytes are final and the owner can vouch
        # for them). None = integrity off: no crc is ever computed, stored,
        # or verified — the exact pre-integrity allocator.
        self._checksum = checksum
        self._crc_of: Dict[int, int] = {}  # physical page id → seal crc
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._refcount: Dict[int, int] = {}
        # sequence_hash → block id, for every block whose contents are valid
        self._by_hash: Dict[int, int] = {}
        self._hash_of: Dict[int, int] = {}  # block id → sequence hash
        # refcount-0 blocks with valid contents, eviction order = lowest
        # class tier first, LRU within a tier (all tier 0 with QoS off)
        self._cached = _TieredLru()
        # QoS (runtime/qos.py): hard-held blocks per tenant (the KV-budget
        # signal) and the class level a block carries into the reuse pool
        # (max over owners — a premium tenant's shared prefix must not be
        # evicted early because a batch tenant also used it). Both dicts
        # stay empty on the single-tenant path.
        self.tenant_blocks: Dict[str, int] = {}
        self._block_level: Dict[int, int] = {}
        # in-flight registry: sequence hash → physical page a live sequence
        # is about to compute into. A concurrent request sharing that prefix
        # waits for the seal instead of prefilling the same content twice.
        self._inflight: Dict[int, int] = {}
        # counters for metrics
        self.hit_tokens = 0
        self.probe_tokens = 0
        self.inflight_waits = 0  # admission deferrals onto an in-flight prefill
        self.shared_prefill_tokens = 0  # tokens served by joining one
        # live occupancy accounting (PR6 telemetry): high-water mark of
        # hard-held (refcounted) blocks and cumulative acquisitions. Peak
        # near num_blocks under normal load means the pool — not slots —
        # is the binding capacity constraint (feeds the SLA planner's
        # pool-resize decision, ROADMAP item 4).
        self.peak_active_blocks = 0
        self.blocks_acquired_total = 0

    def set_sink(self, sink: Optional[KvEventSink]) -> None:
        self._sink = sink

    # -- queries -------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free) + len(self._cached)

    @property
    def active_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def reclaimable_blocks(self) -> int:
        """Blocks with valid contents but refcount 0 (the LRU reuse pool).
        They count as *free* for admission — allocation can evict them — but
        evicting costs future prefix-cache hits; exported separately so the
        overload dashboards can tell hard headroom from warm cache."""
        return len(self._cached)

    def usage(self) -> float:
        return self.active_blocks / self.num_blocks if self.num_blocks else 0.0

    def inflight_pending(self, seq_hash: int) -> bool:
        """Is a live sequence still mid-prefill on this block hash? (Cheap
        check a parked request uses to avoid re-probing its whole prompt.)"""
        return seq_hash in self._inflight

    def hash_of_block(self, block_id: int) -> int:
        """Registered content hash of a physical page, or -1 (free/partial/
        reused pages have none)."""
        return self._hash_of.get(block_id, -1)

    def crc_of_block(self, block_id: int) -> int:
        """Seal-time content checksum of a physical page, or -1 (unsealed,
        or sealed while the integrity plane was off). Ships next to the
        pages on every transfer tier so receivers can verify them."""
        return self._crc_of.get(block_id, -1)

    def blocks_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.block_size - 1) // self.block_size

    def can_allocate(self, n_tokens: int) -> bool:
        # conservative: ignores potential prefix hits
        return self.blocks_needed(n_tokens) <= self.free_blocks

    # -- allocation ----------------------------------------------------------

    def allocate_sequence(
        self, token_ids: Sequence[int], wait_inflight: bool = True,
        tenant: str = "", level: int = 0,
    ) -> Optional[SequenceAllocation]:
        """Allocate pages for a prompt, reusing prefix-cached blocks.

        Returns None if not enough pages are available (caller re-queues),
        or an :class:`InflightPrefix` when ``wait_inflight`` and another live
        sequence is mid-prefill on this prompt's next prefix block (caller
        re-queues; after the owner seals, the retry turns into ordinary
        prefix hits — one prefill compute for N concurrent identical
        prefixes). The last prompt token is never served from cache: its
        logits are needed to sample the first output token, so at least one
        position is computed.
        """
        seq_hashes = compute_block_hashes_for_seq(token_ids, self.block_size, self.salt)
        self.probe_tokens += len(token_ids)

        # longest cached prefix (block-aligned, capped so ≥1 token is computed)
        max_cacheable = min(len(seq_hashes), (len(token_ids) - 1) // self.block_size)
        reused: List[int] = []
        for h in seq_hashes[:max_cacheable]:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            reused.append(bid)

        # host tier continues the chain where the device tier missed; content
        # is captured now so later evictions from the pool can't invalidate
        # it. With the integrity plane on, each entry's bytes are verified
        # against its seal-time checksum HERE — a corrupted entry (bad host
        # RAM) is dropped from the pool and treated as a prefix miss: the
        # chain ends and the prompt recomputes from there, corrupt KV never
        # reaches the device pool.
        host_hits: List[Tuple[int, int, Any, Any, Any, Any, Any]] = []
        if self.host_pool is not None:
            j = len(reused)
            while j < max_cacheable:
                item = self.host_pool.get(seq_hashes[j])
                if item is None:
                    break
                if self._checksum is not None and item[4] is not None:
                    from dynamo_tpu.runtime import integrity

                    if integrity.entry_checksum(*item[:4]) != item[4]:
                        self.host_pool.discard(seq_hashes[j])
                        integrity.note_trip("kv", where="host_rehit")
                        break
                host_hits.append((j, seq_hashes[j]) + tuple(item))
                j += 1

        # shared in-flight prefill: if the next missing block is being
        # computed RIGHT NOW by a live sequence, don't prefill it again
        j0 = len(reused) + len(host_hits)
        if wait_inflight and j0 < max_cacheable and seq_hashes[j0] in self._inflight:
            self.inflight_waits += 1
            return InflightPrefix(seq_hashes[j0])

        # acquire matches FIRST so LRU eviction below can't reclaim them
        for bid in reused:
            self._acquire(bid)

        n_fresh = self.blocks_needed(len(token_ids)) - len(reused)
        if not self._reserve_capacity(n_fresh):
            for bid in reused:  # roll back
                self._release_one(bid)
            return None

        block_ids = list(reused) + [self._take_free() for _ in range(n_fresh)]
        cached_tokens = (len(reused) + len(host_hits)) * self.block_size
        self.hit_tokens += cached_tokens

        # host-hit blocks become valid device content once the engine injects
        # them; register their hashes so the next request hits the device tier
        stored: List[Tuple[int, List[int]]] = []
        for idx, h, *rest in host_hits:
            bid = block_ids[idx]
            prior = self._hash_of.get(bid)
            if prior is not None and prior != h:
                self._unregister(bid)
            if h not in self._by_hash:
                self._by_hash[h] = bid
                self._hash_of[bid] = h
                if self._checksum is not None and rest[4] is not None:
                    # the (verified) host entry's seal checksum describes
                    # the bytes about to be injected into this page
                    self._crc_of[bid] = rest[4]
                stored.append(
                    (h, list(token_ids[idx * self.block_size : (idx + 1) * self.block_size]))
                )
        if stored and self._sink is not None:
            parent = seq_hashes[host_hits[0][0] - 1] if host_hits[0][0] > 0 else None
            self._sink.blocks_stored(parent, stored)

        # advertise the full-prompt blocks this sequence will compute so a
        # concurrent request with the same prefix joins instead of recomputing
        pending: List[int] = []
        for idx in range(j0, len(seq_hashes)):
            h = seq_hashes[idx]
            if h not in self._by_hash and h not in self._inflight:
                self._inflight[h] = block_ids[idx]
                pending.append(h)

        # QoS attribution: budget accounting + eviction-tier tagging (both
        # no-ops on the single-tenant path — tenant ""/level 0)
        if tenant:
            self.tenant_blocks[tenant] = (
                self.tenant_blocks.get(tenant, 0) + len(block_ids)
            )
        if level > 0:
            for bid in block_ids:
                if self._block_level.get(bid, 0) < level:
                    self._block_level[bid] = level

        # hashing state covers only tokens whose KV exists (the cached prefix);
        # note_tokens_computed extends it as prefill/decode computes the rest
        return SequenceAllocation(
            block_ids=block_ids,
            token_blocks=TokenBlockSequence(
                token_ids[:cached_tokens], self.block_size, salt=self.salt
            ),
            cached_tokens=cached_tokens,
            sealed_blocks=len(reused) + len(host_hits),
            host_hits=host_hits,
            pending_hashes=pending,
            tenant=tenant,
            level=level,
        )

    def seed_cached(self, token_ids: Sequence[int]) -> List[Tuple[int, int]]:
        """Register externally-computed KV (pages read from another worker,
        e.g. a decode worker's cached prefix) as prefix-cache content.

        Covers the full blocks of ``token_ids``; returns
        ``[(logical_block_index, physical_block_id)]`` for blocks that were
        NOT already cached — the caller must inject those pages before any
        allocation can hit them (engine thread makes that atomic). Blocks
        whose hash is already resident are skipped. Stops early (partial
        prefix, still correct) if the pool can't yield a free page.

        Seeded blocks land refcount-0 in the LRU reuse pool, exactly like a
        freed sequence's sealed blocks — so a subsequent
        :meth:`allocate_sequence` for a prompt starting with these tokens
        prefix-hits them. Reference semantics: the decode→prefill
        ``read_blocks`` path of the patched vLLM's NIXL connector
        (vllm_v0.7.2 patch nixl.py:1067-1467), where remote prefill reads
        the decode worker's prefix-hit blocks and computes only the rest."""
        n_full = len(token_ids) // self.block_size
        if n_full == 0:
            return []
        covered = token_ids[: n_full * self.block_size]
        seq_hashes = compute_block_hashes_for_seq(covered, self.block_size, self.salt)
        to_inject: List[Tuple[int, int]] = []
        run_stored: List[Tuple[int, List[int]]] = []
        run_parent: Optional[int] = None

        def flush_run():
            if run_stored and self._sink is not None:
                self._sink.blocks_stored(run_parent, list(run_stored))
            run_stored.clear()

        for i, h in enumerate(seq_hashes):
            if h in self._by_hash:
                flush_run()
                run_parent = h
                continue
            if not self._reserve_capacity(1):
                break
            bid = self._take_free()
            self._by_hash[h] = bid
            self._hash_of[bid] = h
            to_inject.append((i, bid))
            if not run_stored:
                run_parent = seq_hashes[i - 1] if i > 0 else None
            run_stored.append(
                (h, list(covered[i * self.block_size : (i + 1) * self.block_size]))
            )
        flush_run()
        # refcount 1 → 0 with a hash ⇒ cached (LRU reuse pool)
        for _, bid in to_inject:
            self._release_one(bid)
        return to_inject

    def grow(self, alloc: SequenceAllocation, n_tokens: int) -> bool:
        """Ensure capacity for a sequence now ``n_tokens`` long (decode growth)."""
        needed = self.blocks_needed(n_tokens)
        while len(alloc.block_ids) < needed:
            if not self._reserve_capacity(1):
                return False
            bid = self._take_free()
            alloc.block_ids.append(bid)
            if alloc.tenant:
                self.tenant_blocks[alloc.tenant] = (
                    self.tenant_blocks.get(alloc.tenant, 0) + 1
                )
            if alloc.level > 0 and self._block_level.get(bid, 0) < alloc.level:
                self._block_level[bid] = alloc.level
        return True

    def note_tokens_computed(self, alloc: SequenceAllocation, token_ids: Sequence[int]) -> None:
        """Record that KV for these tokens now exists in the sequence's pages.

        Seals any blocks that became full: registers their hashes for reuse and
        emits a `stored` event (chain order preserved).
        """
        sealed = alloc.token_blocks.extend(token_ids)
        if not sealed:
            return
        stored: List[Tuple[int, List[int]]] = []
        parent = sealed[0].parent_hash
        for blk in sealed:
            bid = alloc.block_ids[blk.position]
            self._inflight.pop(blk.block_hash, None)  # promise fulfilled
            prior = self._hash_of.get(bid)
            if prior is not None and prior != blk.block_hash:
                self._unregister(bid)  # drops the stale class tag too
                if alloc.level > 0:
                    # the sealing owner's level governs the fresh content
                    self._block_level[bid] = alloc.level
            if blk.block_hash not in self._by_hash:
                self._by_hash[blk.block_hash] = bid
                self._hash_of[bid] = blk.block_hash
                stored.append((blk.block_hash, list(blk.tokens)))
        alloc.sealed_blocks = len(alloc.token_blocks.blocks)
        if self._checksum is not None and stored:
            # seal-time content checksums (docs/resilience.md §Silent
            # corruption): computed exactly once, while the owner can still
            # vouch for the bytes; they travel with the block through every
            # later tier (host spill, transfer frames, migration staging)
            bids = [self._by_hash[h] for h, _ in stored]
            for bid, crc in zip(bids, self._checksum(bids)):
                self._crc_of[bid] = crc
        if stored and self._sink is not None:
            self._sink.blocks_stored(parent, stored)

    def retag_sequence(self, alloc: SequenceAllocation, tenant: str,
                       level: int) -> None:
        """Re-attribute a live allocation to a different tenant/class —
        the receiving side of a live migration adopts staged blocks under
        the checkpoint's tenant, then re-tags them to the attaching
        request's identity (normally the same; a skew must not leave the
        per-tenant budget accounting pointing at the wrong owner)."""
        if tenant != alloc.tenant:
            n = len(alloc.block_ids)
            if alloc.tenant and n:
                left = self.tenant_blocks.get(alloc.tenant, 0) - n
                if left > 0:
                    self.tenant_blocks[alloc.tenant] = left
                else:
                    self.tenant_blocks.pop(alloc.tenant, None)
            if tenant and n:
                self.tenant_blocks[tenant] = (
                    self.tenant_blocks.get(tenant, 0) + n
                )
            alloc.tenant = tenant
        if level != alloc.level:
            alloc.level = level
            # levels only ever rise here (eviction tiering is max-over-
            # owners); a downgrade is corrected when the block's content
            # is replaced (_unregister)
            if level > 0:
                for bid in alloc.block_ids:
                    if self._block_level.get(bid, 0) < level:
                        self._block_level[bid] = level

    def free_sequence(self, alloc: SequenceAllocation) -> None:
        """Release a finished sequence's pages. Hash-registered blocks become
        reusable cache; unhashed (partial) blocks return to the free list.
        Unfulfilled in-flight promises are withdrawn so a waiting request
        stops waiting and computes the prefix itself."""
        own = set(alloc.block_ids)
        for h in alloc.pending_hashes:
            if self._inflight.get(h) in own:
                self._inflight.pop(h, None)
        alloc.pending_hashes = []
        if alloc.tenant and alloc.block_ids:
            left = self.tenant_blocks.get(alloc.tenant, 0) - len(alloc.block_ids)
            if left > 0:
                self.tenant_blocks[alloc.tenant] = left
            else:
                self.tenant_blocks.pop(alloc.tenant, None)
        for bid in alloc.block_ids:
            self._release_one(bid)
        alloc.block_ids = []

    # -- internals -----------------------------------------------------------

    def _release_one(self, bid: int) -> None:
        rc = self._refcount.get(bid, 0) - 1
        if rc > 0:
            self._refcount[bid] = rc
            return
        self._refcount.pop(bid, None)
        if bid in self._hash_of:
            # reuse pool, tiered by the owners' class level: lowest class
            # evicted first under pressure (0 for everything with QoS off)
            self._cached.add(bid, self._block_level.get(bid, 0))
        else:
            self._block_level.pop(bid, None)
            self._free.append(bid)

    def _acquire(self, bid: int) -> None:
        self._cached.discard(bid)  # revive from reuse pool
        self._refcount[bid] = self._refcount.get(bid, 0) + 1
        self._note_occupancy()

    def _take_free(self) -> int:
        bid = self._free.pop()
        self._refcount[bid] = 1
        self._note_occupancy()
        return bid

    def _note_occupancy(self) -> None:
        self.blocks_acquired_total += 1
        active = self.active_blocks
        if active > self.peak_active_blocks:
            self.peak_active_blocks = active

    def peak_occupancy(self) -> float:
        """High-water fraction of the pool ever hard-held at once."""
        return (
            self.peak_active_blocks / self.num_blocks if self.num_blocks else 0.0
        )

    def _reserve_capacity(self, n: int) -> bool:
        """Make sure the free list has n entries, evicting LRU cached blocks.

        Evicted blocks spill to the host tier (offload callback copies their
        still-valid device contents) before their pages are reusable."""
        evicted: List[int] = []
        spill: List[Tuple[int, int, Any]] = []
        while len(self._free) < n:
            bid = self._cached.pop_oldest()  # lowest class tier, then LRU
            if bid is None:
                return False
            h = self._hash_of.pop(bid)
            del self._by_hash[h]
            self._block_level.pop(bid, None)
            evicted.append(h)
            # the seal-time checksum follows the content into the host tier
            # (verified at rehit); the page itself is being recycled
            crc = self._crc_of.pop(bid, None)
            if self._offload is not None and self.host_pool is not None:
                if h not in self.host_pool:
                    spill.append((h, bid, crc))
            self._free.append(bid)
        if spill:
            self._offload(spill)
        if evicted and self._sink is not None:
            self._sink.blocks_removed(evicted)
        return True

    def _unregister(self, bid: int) -> None:
        h = self._hash_of.pop(bid, None)
        if h is not None:
            self._by_hash.pop(h, None)
            if self._sink is not None:
                self._sink.blocks_removed([h])
        # content replaced ⇒ its seal checksum no longer describes the page
        self._crc_of.pop(bid, None)
        self._cached.discard(bid)
        # the block's content is being replaced: its class tag must not
        # survive into the new owner's tier (levels only ever go UP via
        # allocate/grow — a stale high tag would shelter a low-class
        # block from eviction forever)
        self._block_level.pop(bid, None)
