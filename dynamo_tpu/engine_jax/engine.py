"""The continuous-batching JAX serving engine.

Architecture (TPU-first, cf. SURVEY.md §7 stage 4):

- **Fixed batch slots**: `max_slots` decode lanes; a request occupies one slot
  from first token to finish. All decode steps run ONE jitted function with
  static shapes — no recompilation, ever.
- **Chunked, batched prefill**: every step with a prefilling lane runs ONE
  compiled `[slots, prefill_chunk]` function in which prefilling lanes consume
  up to `prefill_chunk` prompt tokens while decode lanes advance one token —
  prefill never runs batch-1 and never blocks decode for more than a chunk.
  Prompts longer than a chunk just take several steps (long-context prefill is
  chunked by construction; no shape depends on prompt length).
- **Paged KV**: allocator (allocator.py) maps sequences onto a page pool in
  HBM with content-addressed prefix reuse; the model writes-then-attends
  through block tables (models/llama.py), making prefix hits free.
- **In-jit sampling** (sampling.py): only token ids cross to host per step.
- **Step loop on a dedicated thread**: jax dispatch blocks, asyncio must not.
  Tokens stream to requesters via `loop.call_soon_threadsafe` into per-request
  asyncio queues — this is how tokens cross the jit/async boundary.

The engine implements the framework AsyncEngine interface (token-in/token-out,
like the reference's ExecutionContext engines, SURVEY.md §2.5) so it slots into
the same pipelines as the echo engines and remote clients.
"""

from __future__ import annotations

import asyncio
import logging
import math
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine_jax.allocator import (
    BlockAllocator,
    HostKvPool,
    InflightPrefix,
    KvDtypeMismatch,
    KvEventSink,
    MigrationRejected,
    SequenceAllocation,
)
from dynamo_tpu.engine_jax.drafter import (
    MAX_SPEC_K,
    NgramDrafter,
    env_kv_dtype,
    env_spec_k,
    env_spec_ngram,
)
from dynamo_tpu.engine_jax.sampling import (
    apply_penalties,
    sample_tokens,
    speculative_targets,
    token_logprobs,
    update_counts,
)
from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.models.llama import (
    LlamaConfig,
    dequantize_kv,
    flush_window,
    forward,
    forward_chunk,
    forward_window,
    gather_history,
    lm_head,
    make_kv_cache,
    quantize_kv,
)
from dynamo_tpu.engine_jax.compile_cache import compile_count, record_compile
from dynamo_tpu.runtime import faults as faults_mod
from dynamo_tpu.runtime import integrity as integrity_mod
from dynamo_tpu.runtime import profiling as profiling_mod
from dynamo_tpu.runtime import qos as qos_mod
from dynamo_tpu.runtime import straggler as straggler_mod
from dynamo_tpu.runtime import telemetry, tracing
from dynamo_tpu.runtime.integrity import WATCHDOG_TOKEN
from dynamo_tpu.runtime.annotated import Annotated
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.health import EngineHeartbeat

logger = logging.getLogger(__name__)


class _EnginePerf:
    """Live decode-perf accounting (engine thread only, EMA-smoothed).

    The BENCH files compute tokens/s and roofline fractions *offline*; this
    makes the same signals live gauges on the metrics stream
    (``ForwardPassMetrics.decode_tokens_per_s`` etc.) so the telemetry
    plane — and eventually the SLA planner — can see a decode regression as
    it happens. Built only when telemetry sampling is enabled
    (``DYN_TPU_SLO=0`` ⇒ the engine holds ``None`` and the step loop pays
    one attribute check, asserted by ``tests/test_telemetry.py``).

    Timing anchors on the gap between consecutive *processed* decode chunks
    (which in pipelined decode equals the chunk's wall time); idle gaps are
    excluded via :meth:`note_idle` so a quiet engine's throughput gauge
    reflects its last busy period instead of decaying toward zero.
    """

    __slots__ = (
        "decode_tps", "step_time_ms", "slot_util", "spec_accept_rate",
        "_last_t", "_alpha",
    )

    def __init__(self, alpha: float = 0.2):
        self.decode_tps = 0.0
        self.step_time_ms = 0.0
        self.slot_util = 0.0
        # acceptance-rate EMA over verify dispatches (accepted drafts /
        # drafted); 0.0 with speculation off or before the first draft
        self.spec_accept_rate = 0.0
        self._last_t: Optional[float] = None
        self._alpha = alpha

    def _ema(self, prev: float, sample: float) -> float:
        return sample if prev == 0.0 else prev + self._alpha * (sample - prev)

    def note_decode(self, n_tokens: int, k_steps: int) -> None:
        now = time.perf_counter()
        last, self._last_t = self._last_t, now
        if last is None:
            return
        dt = now - last
        if dt <= 0:
            return
        if n_tokens > 0:
            self.decode_tps = self._ema(self.decode_tps, n_tokens / dt)
        self.step_time_ms = self._ema(
            self.step_time_ms, dt * 1e3 / max(k_steps, 1)
        )

    def note_slots(self, active: int, total: int) -> None:
        if total > 0:
            self.slot_util = self._ema(self.slot_util, active / total)

    def note_spec(self, drafted: int, accepted: int) -> None:
        if drafted > 0:
            self.spec_accept_rate = self._ema(
                self.spec_accept_rate, accepted / drafted
            )

    def note_idle(self) -> None:
        self._last_t = None


@dataclass(frozen=True)
class EngineConfig:
    max_slots: int = 8
    kv_block_size: int = 16
    max_model_len: int = 2048
    num_kv_blocks: Optional[int] = None  # default: 1.5× what max_slots need
    # tokens of prompt consumed per prefilling lane per step — the unit of
    # prefill/decode interleaving (a decode lane is delayed at most one
    # chunk's compute by any admission wave)
    prefill_chunk: int = 128
    # decode steps per device dispatch: each dispatch scans this many
    # forward+sample steps in one jitted call, amortizing host↔device latency
    # (critical when dispatch rides a network tunnel). Tokens past a stop
    # condition are discarded host-side; worst case wastes decode_steps-1
    # token computations per finished request.
    decode_steps: int = 1
    # safety net for disaggregated prefill: a sequence whose remote prefill
    # hasn't landed within this window falls back to local prefill
    remote_prefill_timeout: float = 60.0
    # host-RAM KV tier: evicted device blocks spill here and re-enter HBM on
    # a prefix hit (0 = disabled). Sized in blocks; reference credits the
    # equivalent pinned-host tier with +40% TTFT on multi-turn (BASELINE.md).
    host_cache_blocks: int = 0
    # alternatives computed per step for OpenAI logprobs; matches OpenAI's
    # documented top_logprobs bound so a validated request is never silently
    # truncated. Computed (and transferred) only when a request asks.
    top_logprobs: int = 20
    # admission-wave coalescing: when the engine is idle and requests are
    # still arriving, wait up to this long (seconds) for the wave to finish
    # landing so every prompt prefills in ONE chunk dispatch instead of the
    # stragglers eating a whole extra chunk of TTFT. A lone request pays at
    # most one poll interval (~3 ms); an idle engine with a full wave pays
    # nothing extra at all (the wave fills the slots and the wait ends).
    admission_window: float = 0.02
    # budget for the dense decode-history buffer ([L, S, max_model_len] K+V,
    # gathered once per decode dispatch). Under it: dense windowed decode
    # (faster — measured ~1.4x over paged DMA at 2k ctx on v5e). Over it:
    # the Pallas kernel streams live pages from HBM with zero extra
    # residency (the 70B/long-context regime). DYN_TPU_ATTENTION overrides.
    dense_history_max_bytes: int = 2 << 30
    # weight-only quantization: "int8" halves the decode weight stream
    # (per-output-channel absmax, models/llama.py quantize_params_int8).
    # Single-chip path; mesh-sharded configs keep bf16.
    quantize: Optional[str] = None
    # self-draft speculative decoding: number of n-gram-drafted tokens
    # verified per decode dispatch (engine_jax/drafter.py). None = read
    # DYN_TPU_SPEC_K (default 0 = off); values clamp to [0, MAX_SPEC_K].
    # Every accepted draft amortizes one full decode weight stream.
    spec_k: Optional[int] = None
    # longest trailing n-gram the drafter probes (None = DYN_TPU_SPEC_NGRAM,
    # default 3)
    spec_ngram: Optional[int] = None
    # multi-tenant QoS (runtime/qos.py): prefill duty-cycle budget — the
    # AVERAGE prefill tokens allowed per engine dispatch while decode
    # lanes are live. A chunk dispatch costs full [S, C] compute and
    # advances decode lanes only one token, so isolation works by pacing
    # chunk-dispatch frequency: one chunk, then ~chunk/budget pure
    # pipelined decode dispatches. Long prompts raise their OWN TTFT
    # instead of spiking every decode lane's ITL; an engine with no
    # decode lanes prefills at full speed. None = read
    # DYN_TPU_PREFILL_BUDGET (clamped; default 0 = unlimited, the pre-QoS
    # behavior).
    prefill_budget: Optional[int] = None
    # KV page storage dtype: "bf16" (native — actually the cache_dtype /
    # model dtype) or "int8" (quantized pages + per-block scale tables,
    # halving the KV half of the decode stream at long context). None =
    # read DYN_TPU_KV_DTYPE. int8 KV is single-chip (mesh=None) for now and
    # pins the dense decode-history tier (the Pallas kernel has no fused
    # dequant yet — ROADMAP item 2 pairs them).
    kv_dtype: Optional[str] = None

    def resolve_num_blocks(self) -> int:
        if self.num_kv_blocks is not None:
            return self.num_kv_blocks
        per_seq = math.ceil(self.max_model_len / self.kv_block_size)
        return int(self.max_slots * per_seq * 3 // 2)

    @property
    def max_blocks_per_seq(self) -> int:
        return math.ceil(self.max_model_len / self.kv_block_size)


class _Seq:
    """One in-flight request's host-side state."""

    __slots__ = (
        "ctx", "request", "prompt", "alloc", "slot", "out_queue", "loop",
        "generated", "emitted", "max_tokens", "eos_ids", "ignore_eos",
        "temperature", "top_k", "top_p", "seed", "logprobs", "enqueue_t",
        "first_token_t", "admit_t", "remote", "remote_deadline", "prefill_pos",
        "freq_pen", "pres_pen", "out_tokens", "joined_inflight", "wait_hash",
        "drafter", "spec_drafted", "spec_accepted", "tenant", "level",
        "weight", "resumed", "migrated",
    )

    def __init__(self, ctx: Context, request: PreprocessedRequest, loop) -> None:
        self.ctx = ctx
        self.request = request
        self.prompt: List[int] = list(request.token_ids)
        self.alloc: Optional[SequenceAllocation] = None
        self.slot: Optional[int] = None
        self.out_queue: asyncio.Queue = asyncio.Queue()
        self.loop = loop
        self.generated: List[int] = []
        # tokens streamed to the caller — survives preemption (generated is
        # absorbed into prompt on preempt, so it can't back max_tokens)
        self.emitted = 0
        sc = request.stop_conditions
        self.max_tokens = sc.max_tokens if sc.max_tokens is not None else 2**30
        self.eos_ids: Set[int] = set(request.eos_token_ids or [])
        self.ignore_eos = bool(sc.ignore_eos)
        so = request.sampling_options
        self.temperature = so.temperature if so.temperature is not None else 0.0
        self.top_k = so.top_k if so.top_k is not None else 0
        self.top_p = so.top_p if so.top_p is not None else 1.0
        self.seed = so.seed if so.seed is not None else 0
        self.freq_pen = so.frequency_penalty or 0.0
        self.pres_pen = so.presence_penalty or 0.0
        # all output tokens ever emitted — unlike `generated`, survives
        # preemption; rebuilds the device penalty-count row on re-admission
        self.out_tokens: List[int] = []
        # mid-stream resume (runtime/resilience.StreamJournal wire marker):
        # token_ids[prompt_len:] are ANOTHER worker's already-emitted output
        # riding in as prompt. Pre-seeding out_tokens hands them to the same
        # _sync_counts rebuild that preemption uses, so frequency/presence
        # penalties continue exactly where the dead stream left off —
        # identical machinery, zero new device code. Positions/KV treat the
        # full token_ids as prompt (that IS the recompute; the prefix cache
        # and host tier soften it like any preemption recompute).
        self.resumed = False
        # live migration (disagg/migration.py): set at admission when this
        # request adopted a staged migration's allocation — its "prefill"
        # is one fresh position, not a recompute
        self.migrated = False
        res = getattr(request, "resume", None)
        if isinstance(res, dict):
            try:
                plen = int(res.get("prompt_len", 0))
            except (TypeError, ValueError):
                plen = 0
            if 0 < plen <= len(self.prompt):
                self.resumed = True
                self.out_tokens = list(self.prompt[plen:])
        # None = don't emit logprobs; 0 = chosen only; k = with alternatives
        self.logprobs = so.logprobs
        self.enqueue_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        # first slot admission (tracing: queue_wait ends, prefill begins);
        # preemption re-admissions keep the original stamp
        self.admit_t: Optional[float] = None
        self.remote = False  # prefill dispatched to a remote prefill worker
        self.remote_deadline: Optional[float] = None
        self.joined_inflight = False  # parked behind a concurrent identical prefix
        self.wait_hash: Optional[int] = None  # the in-flight hash it's parked on
        # next prompt position to compute while prefilling; None = decoding
        self.prefill_pos: Optional[int] = None
        # self-draft speculation (engine_jax/drafter.py): the engine attaches
        # a per-sequence NgramDrafter only when spec_k > 0 — None keeps the
        # spec-off step loop allocation-free (the same None-check pattern as
        # _EnginePerf). Counters feed the per-request acceptance attributes
        # on the engine.decode span and the spec_accept phase histogram.
        self.drafter = None
        self.spec_drafted = 0
        self.spec_accepted = 0
        # multi-tenant QoS (runtime/qos.py): tenant id + class level/weight
        # stamped by generate() when QoS is on (or a bare tenant id for
        # attribution when off). Defaults keep the single-tenant step loop
        # on the zero-bookkeeping path.
        self.tenant = ""
        self.level = 0
        self.weight = 1.0

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.generated)

    @property
    def penalized(self) -> bool:
        return self.freq_pen != 0.0 or self.pres_pen != 0.0

    def emit(self, item) -> None:
        # The consumer's event loop can die under us (client teardown, a
        # finished asyncio.run) while the engine is still processing this
        # sequence's speculative chunk. Emitting into a dead loop can wedge
        # the ENGINE THREAD in call_soon_threadsafe's self-pipe write —
        # observed as permanently leaked blocks + a stuck step loop. Nobody
        # can receive these items; drop them.
        if self.loop.is_closed():
            return
        try:
            self.loop.call_soon_threadsafe(self.out_queue.put_nowait, item)
        except RuntimeError:
            pass  # loop closed between the check and the call


_FINISHED = object()  # sentinel closing a request's output queue


class _DevMirror:
    """Host→device upload cache: re-uploads only when the host array changed.

    On a tunneled chip every `jnp.asarray` is a separate transfer with
    fixed latency; the sampling vectors change only on lane changes, so in
    steady-state decode they hit this cache every dispatch."""

    __slots__ = ("_host", "_dev", "_put")

    def __init__(self, put=None):
        self._host: Optional[np.ndarray] = None
        self._dev = None
        self._put = put or jnp.asarray

    def get(self, host_arr: np.ndarray):
        if self._dev is None or not np.array_equal(self._host, host_arr):
            self._host = host_arr.copy()
            self._dev = self._put(host_arr)
        return self._dev


class _Inflight:
    """A dispatched-but-unprocessed decode chunk (pipelined decode).

    Holds device handles for the chunk's sampled tokens and the final carry
    (last token + position per lane), plus the lane→sequence snapshot at
    dispatch time. The engine dispatches chunk N+1 off these handles before
    fetching chunk N's results, hiding the host↔device round trip behind
    compute — on a tunneled chip that round trip is ~90 ms, comparable to the
    whole chunk's compute.
    """

    __slots__ = ("out", "lps", "top_ids", "top_lps", "tokens", "positions", "lanes")

    def __init__(self, out, lps, top_ids, top_lps, tokens, positions, lanes):
        self.out = out  # [S, k_steps] device
        self.lps = lps  # [S, k_steps] device, chosen-token logprobs
        self.top_ids = top_ids  # [S, k_steps, P] device
        self.top_lps = top_lps  # [S, k_steps, P] device
        self.tokens = tokens  # [S] device, final carry
        self.positions = positions  # [S] device, final carry
        self.lanes = lanes  # List[Optional[_Seq]] snapshot


class JaxServingEngine(AsyncEngine):
    """Continuous-batching paged-KV engine over a jitted Llama step."""

    def __init__(
        self,
        model_config: LlamaConfig,
        params: Any,
        engine_config: EngineConfig = EngineConfig(),
        mesh=None,
        event_sink: Optional[KvEventSink] = None,
        cache_dtype: Any = None,
    ):
        self.model_config = model_config
        self.config = engine_config
        if engine_config.quantize == "int8-all":
            # int8 for BOTH phases, bf16 tree dropped: the fit mode for
            # models whose bf16 weights alone exceed the chip (llama3-8b =
            # 16.06 GB on a 16 GB v5e). Prefill pays the dequant cost;
            # callers with host-quantized trees pass them directly so the
            # full bf16 tree never has to exist in HBM.
            from dynamo_tpu.models.llama import quantize_params_int8

            def _is_quantized(tree):
                lay = tree.get("layers", {}) if isinstance(tree, dict) else {}
                return isinstance(lay.get("wq"), dict)

            qp = (
                params if _is_quantized(params)
                else quantize_params_int8(params, model_config)
            )
            self.params = params = qp
            self.params_decode = qp
        elif engine_config.quantize == "int8":
            from dynamo_tpu.models.llama import quantize_params_int8

            # hybrid: DECODE reads the int8 copy (weights are the decode
            # bandwidth roofline — the stream halves), PREFILL keeps bf16
            # (it is FLOPs-bound and per-tile dequant converts starve the
            # MXU — measured 13x slower chunks). Costs 1.5x param residency.
            if mesh is not None:
                # sharded serving: quantize under jit with out_shardings so
                # each {q, s} leaf lands sharded like its parent weight
                # (scales keep every non-contracted axis) — the 70B north
                # star serves int8 on the dp×tp mesh. Works on a process-
                # spanning mesh too: every host runs this jit in lockstep.
                from dynamo_tpu.models.llama import quantized_param_shardings

                quant = jax.jit(
                    lambda p: quantize_params_int8(p, model_config),
                    out_shardings=quantized_param_shardings(model_config, mesh),
                )
                self.params_decode = quant(params)
            else:
                self.params_decode = quantize_params_int8(params, model_config)
        elif engine_config.quantize:
            raise ValueError(f"unknown quantize mode {engine_config.quantize!r}")
        else:
            self.params_decode = params
        self.params = params
        self.mesh = mesh
        # self-draft speculative decoding knobs (engine_jax/drafter.py):
        # config wins when set, else the clamped env parsers. spec_k = 0 is
        # the off default — the decode path then never touches a drafter.
        sk = (
            engine_config.spec_k if engine_config.spec_k is not None
            else env_spec_k()
        )
        self._spec_k = max(0, min(int(sk), MAX_SPEC_K))
        self._spec_ngram = (
            engine_config.spec_ngram if engine_config.spec_ngram is not None
            else env_spec_ngram()
        )
        # KV page storage dtype: int8 pages + per-token scale tables halve
        # the KV half of the decode stream. Single-chip only for now — the
        # sharded cache path and the Pallas kernel have no dequant tier yet
        # (ROADMAP item 2 pairs them).
        if engine_config.kv_dtype not in (None, "bf16", "int8"):
            # the env parser deliberately degrades typos to the native
            # layout (a typo must never silently quantize a fleet), but an
            # explicit config value is a programming error: "INT8" silently
            # measuring bf16 would invalidate a whole benchmark run
            raise ValueError(
                f"kv_dtype={engine_config.kv_dtype!r} not in "
                "{None, 'bf16', 'int8'}"
            )
        kd = engine_config.kv_dtype or env_kv_dtype()
        self._kv_quantized = kd == "int8"
        if self._kv_quantized and mesh is not None:
            raise ValueError(
                "kv_dtype='int8' requires an unsharded cache (mesh=None); "
                "sharded engines keep the native KV dtype"
            )
        # multihost lockstep: every host array entering a global-mesh jit is
        # built as a replicated global array (jnp.asarray cannot span
        # processes); single-host configs take the plain path
        self._multihost = mesh is not None and jax.process_count() > 1
        self._dispatch_hook = None  # multihost leader: broadcast dispatches
        self.num_blocks = engine_config.resolve_num_blocks()
        self.host_pool = (
            HostKvPool(engine_config.host_cache_blocks)
            if engine_config.host_cache_blocks > 0
            else None
        )
        # integrity plane (runtime/integrity.py, docs/resilience.md §Silent
        # corruption): block content checksums at seal + the output
        # watchdog. None with DYN_TPU_KV_INTEGRITY=0 — THE zero-overhead
        # gate: no checksum callback is installed, no watchdog variant is
        # built, every jitted program is exactly the pre-integrity one.
        self._integrity = integrity_mod.maybe_from_env()
        # the watchdog rides the jitted step functions as one extra scalar
        # input + a sentinel substitution; sharded/multihost engines keep
        # the pre-integrity dispatch protocol (followers replay the
        # leader's opcode stream — an extra input would skew it), so the
        # watchdog is single-chip for now, like int8 KV.
        self._watchdog = self._integrity is not None and mesh is None
        # label the fault gates match on ("corrupt"/"poison" drills target
        # ONE worker in a fleet); attach_kv_publishing stamps the worker id
        self._fault_addr = "engine"
        self.allocator = BlockAllocator(
            self.num_blocks, engine_config.kv_block_size, event_sink=event_sink,
            host_pool=self.host_pool,
            offload=self._offload_blocks if self.host_pool is not None else None,
            checksum=(
                self._block_checksums if self._integrity is not None else None
            ),
        )

        # attention impl is auto-selected (platform + head-dim rule,
        # ops/attention.py); on a sharded cache the kernel runs per-tp-shard
        # under shard_map — `mesh` is passed into forward so the kernel tier
        # stays live in sharded (70B-path) configs instead of falling back
        # to jnp. The pool is created ON-device via out_shardings (zeros
        # never round-trip the host, and on a multi-process mesh each host
        # materializes only its shards — device_put cannot span processes).
        cshape = (
            model_config.num_layers, self.num_blocks,
            engine_config.kv_block_size, model_config.num_kv_heads,
            model_config.head_dim,
        )
        cdtype = cache_dtype or model_config.dtype
        # compute dtype of attention inputs: int8 pages dequantize into this
        # (and the decode window buffers are allocated in it — never in the
        # pool's storage dtype)
        self._compute_dtype = cdtype
        if mesh is not None:
            from dynamo_tpu.parallel.mesh import kv_cache_sharding

            sh = kv_cache_sharding(mesh)
            make = jax.jit(
                lambda: {"k": jnp.zeros(cshape, cdtype), "v": jnp.zeros(cshape, cdtype)},
                out_shardings={"k": sh, "v": sh},
            )
            self.cache = make()
        else:
            self.cache = make_kv_cache(
                model_config, self.num_blocks, engine_config.kv_block_size,
                dtype=cdtype, quantized=self._kv_quantized,
            )

        S = engine_config.max_slots
        MB = engine_config.max_blocks_per_seq
        self._slots: List[Optional[_Seq]] = [None] * S
        self._tables = np.zeros((S, MB), np.int32)
        self._last_tokens = np.zeros((S,), np.int32)
        self._positions = np.full((S,), -1, np.int32)
        self._temp = np.zeros((S,), np.float32)
        self._topk = np.zeros((S,), np.int32)
        self._topp = np.ones((S,), np.float32)
        self._seeds = np.zeros((S,), np.int32)
        self._freqp = np.zeros((S,), np.float32)
        self._presp = np.zeros((S,), np.float32)

        # frequency/presence penalties: [S, V] output-token count buffer,
        # device-resident, maintained in-jit (sampling.apply_penalties /
        # update_counts). Allocated lazily on the first penalized request;
        # the dummy stands in when no lane is penalized so the two step-fn
        # variants share one signature. `_counts_lanes` records which _Seq
        # each row's contents belong to (identity), so admissions into a
        # slot reset + rebuild only the rows that changed.
        self._counts: Optional[jax.Array] = None
        if self._multihost:
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())
            self._dummy_counts = jax.jit(
                lambda: jnp.zeros((S, 1), jnp.int32), out_shardings=rep
            )()
        else:
            self._dummy_counts = jnp.zeros((S, 1), jnp.int32)
        # upload caches for the per-dispatch host arrays (see _DevMirror)
        self._m_tables = _DevMirror(self._put)
        self._m_ipack = _DevMirror(self._put)
        self._m_fpack = _DevMirror(self._put)
        self._counts_lanes: List[Optional[_Seq]] = [None] * S
        self._counts_sync_fns: Dict[Tuple[int, int], Any] = {}
        self._counts_fix_fns: Dict[int, Any] = {}

        self._step_counter = 0

        self._pending: Deque[_Seq] = deque()
        self._cond = threading.Condition()
        self._shutdown = False
        self._thread: Optional[threading.Thread] = None

        # pipelined decode: at most one dispatched-but-unprocessed chunk, plus
        # allocations whose blocks may still receive speculative writes from
        # the in-flight chunk (freed only once it has been fetched)
        self._inflight: Optional[_Inflight] = None
        self._zombie_allocs: List[SequenceAllocation] = []

        # disaggregated prefill: policy decides + submits; sequences wait in
        # _awaiting until the prefill worker's KV lands (complete_remote_prefill)
        self._remote_policy: Optional[Any] = None
        self._awaiting: Dict[str, _Seq] = {}
        self._posted: Deque[Any] = deque()  # host fns to run on the engine thread
        # serializes posted-callback execution once close() removes the
        # engine thread as the single executor (post-close inline runs).
        # Reentrant: a posted callback may itself post (e.g. a failed
        # complete_remote_prefill falls back via fail_remote_prefill), and
        # post-close that nested post runs inline on the same thread.
        self._posted_exec_lock = threading.RLock()

        # prefill-worker mode: requests whose pages are parked on finish so
        # the worker can extract them (hold_pages / take_held_pages)
        self._hold_ids: set = set()
        self._held_allocs: Dict[str, SequenceAllocation] = {}

        # host-tier spills in flight: (pairs, k_dev, v_dev, k_scale_dev,
        # v_scale_dev) whose async host copies haven't been harvested into
        # the host pool yet (scale entries None for native-dtype pools)
        self._pending_spills: Deque[
            Tuple[List[Tuple[int, int]], Any, Any, Any, Any]
        ] = deque()

        # live in-flight migration (disagg/migration.py, docs/resilience.md
        # §Live migration). Source side: sequences frozen out of their slots
        # while the drain coordinator ships their pages. Target side: staged
        # imports — a pre-built allocation whose cached_tokens covers every
        # already-computed position, keyed by migration id, waiting for the
        # re-homed client's attach (TTL-swept if it never comes). Both dicts
        # stay empty unless a drain migration is actually in flight — the
        # step loop pays nothing for the feature existing.
        self._migrating_out: Dict[str, _Seq] = {}
        self._staged_migrations: Dict[str, Tuple[SequenceAllocation, tuple, float]] = {}

        # stats
        self.total_requests = 0
        self.total_generated_tokens = 0
        self.total_prompt_tokens = 0
        self.preemptions = 0
        # mid-stream resume (docs/resilience.md): requests admitted with a
        # resume marker — their prompt is another worker's dead stream
        self.resumed_requests = 0
        # live migration counters: streams this engine shipped out on drain,
        # staged imports adopted by a re-homed client, and — the chaos-gate
        # observable — prompt positions a RESUMED/MIGRATED admission had to
        # recompute (a migrated stream adds 0; a plain resume adds the whole
        # uncached history)
        self.migrated_out_requests = 0
        self.migrated_in_requests = 0
        self.migrations_failed = 0
        self.resume_recompute_tokens = 0
        # output watchdog (docs/resilience.md §Silent corruption): lanes
        # whose dispatch produced non-finite/exploding logits — each ended
        # typed and in-band (resume directive) before any token reached a
        # client, and counted as an integrity trip against this worker
        self.watchdog_trips = 0
        # speculative decoding (cumulative): drafts handed to verify
        # dispatches and how many matched their sampled targets
        self.spec_drafted_total = 0
        self.spec_accepted_total = 0

        # health plane: the step loop beats this once per iteration; a busy
        # engine whose beats stop is a wedged engine thread (device hang,
        # deadlocked posted callback) — runtime/health.py HealthMonitor
        # turns that into an `unhealthy` self-drain
        self.heartbeat = EngineHeartbeat()

        # live perf accounting (telemetry plane): None when sampling is off,
        # so the step loop's only cost is this attribute's None-check
        self._perf: Optional[_EnginePerf] = (
            _EnginePerf() if telemetry.enabled() else None
        )

        # performance attribution plane (runtime/profiling.py,
        # docs/observability.md §Profiling): per-dispatch device/host/alloc
        # timing into the process-global StepTimeline ring. None with
        # DYN_TPU_PROFILE off — the step loop then pays one None-check per
        # dispatch and no timeline is ever constructed (the zero-overhead
        # guard in tests/test_profiling.py monkeypatches the constructor).
        self._profile = profiling_mod.maybe_from_env()
        self._timeline = (
            profiling_mod.timeline() if self._profile is not None else None
        )
        # allocator microseconds (alloc/grow/evict/seal-checksum) accrued
        # since the last dispatch record — admission allocs between
        # dispatches charge the NEXT dispatch's record
        self._prof_alloc_us = 0.0

        # fail-slow defense (runtime/straggler.py, docs/resilience.md
        # §Fail-slow): per-dispatch wall-us-per-token EWMA feeding the
        # aggregator's differential straggler verdicts. None with
        # DYN_TPU_STRAGGLER off — the step loop then pays one None-check
        # per dispatch and no detector is ever constructed (the
        # zero-overhead guard in tests/test_straggler.py monkeypatches
        # the constructor). Independent of the profiling plane: the
        # straggler feed needs EVERY dispatch's coarse wall split, not a
        # sampled block-until-ready capture.
        self._straggler = straggler_mod.maybe_detector()

        # multi-tenant QoS (runtime/qos.py, docs/qos.md): policy + weighted
        # fair-queue bookkeeping, built ONLY when DYN_TPU_TENANT_* knobs are
        # set — the single-tenant step loop pays one None-check (asserted by
        # tests/test_qos.py's zero-overhead guard, the _EnginePerf pattern).
        self._qos = qos_mod.maybe_from_env()
        self._fair: Optional[qos_mod.FairQueue] = (
            qos_mod.FairQueue(self._qos.max_tenants)
            if self._qos is not None else None
        )
        # prefill duty-cycle budget (chunked-prefill interleaving): average
        # prefill tokens per dispatch while decode lanes are live; config
        # wins when set, else the clamped env knob; 0 = unlimited. The
        # debt counter is the duty-cycle state (see _dispatch_step).
        pb = engine_config.prefill_budget
        self._prefill_budget = (
            qos_mod.env_prefill_budget() if pb is None else max(int(pb), 0)
        )
        self._prefill_debt = 0.0
        # per-tenant KV-block budget: binds only while other tenants are
        # active (work-conserving — a tenant alone may use the whole pool)
        self._tenant_kv_budget = (
            max(1, int(self._qos.kv_frac * self.num_blocks))
            if self._qos is not None and self._qos.kv_frac > 0
            else 0
        )
        # per-tenant decode-slot budget: the same work-conserving contract
        # over concurrency — a tenant at its slot share defers while other
        # tenants are active, and alone it may fill the whole batch
        self._tenant_slot_budget = (
            max(1, int(self._qos.slot_frac * engine_config.max_slots))
            if self._qos is not None and self._qos.slot_frac > 0
            else 0
        )
        # high-water mark of prefill tokens computed in a single dispatch
        # that also carried a decode lane — the chunked-prefill interleaving
        # bound the ITL-isolation test asserts against the step budget
        self.prefill_interleave_max = 0

        # (with_logprobs, with_penalties, with_sampling) variants, compiled
        # lazily per need
        self._decode_fns: Dict[Tuple[bool, bool, bool], Any] = {}
        self._chunk_fns: Dict[Tuple[bool, bool, bool], Any] = {}
        # speculative-verify variants (same key space); never built with
        # spec_k == 0 — asserted by the zero-overhead guard test
        self._verify_fns: Dict[Tuple[bool, bool, bool], Any] = {}

        # decode history tier, fixed at build time (the attention policy env
        # vars are read here rather than per-trace). Both tiers are window-
        # buffered; see ops/attention.py decode_uses_pallas for the policy.
        from dynamo_tpu.ops.attention import decode_uses_pallas

        mc, ec = model_config, engine_config
        dtype_size = jnp.dtype(cache_dtype or mc.dtype).itemsize
        hist_bytes = (
            2 * mc.num_layers * ec.max_slots * ec.max_blocks_per_seq
            * ec.kv_block_size * mc.num_kv_heads * mc.head_dim * dtype_size
        )
        self._decode_dense = not decode_uses_pallas(
            mc.head_dim, mesh, mc.num_heads, mc.num_kv_heads,
            dense_history_bytes=hist_bytes,
            dense_history_budget=ec.dense_history_max_bytes,
        )
        if self._kv_quantized:
            # the Pallas kernel has no fused dequant: int8 pools pin the
            # dense decode-history tier (gather_history dequantizes). The
            # dense buffer is transient compute-dtype working set the
            # einsums needed anyway; the HBM *read* is the halved int8 one.
            self._decode_dense = True

        # pipeline parallelism: when the mesh has a pp axis > 1, step fns
        # route through parallel/pipeline.py's GPipe schedule (layer stages
        # + microbatched slots over ICI ppermute) instead of the
        # single-program layer scan
        from dynamo_tpu.parallel.mesh import AXIS_PP, AXIS_SP

        self._pp = (
            mesh.shape[AXIS_PP]
            if mesh is not None and AXIS_PP in mesh.axis_names
            else 1
        )
        if self._pp > 1:
            if mc.num_layers % self._pp:
                raise ValueError(
                    f"num_layers {mc.num_layers} not divisible by pp {self._pp}"
                )
            if ec.max_slots % self._pp:
                raise ValueError(
                    f"max_slots {ec.max_slots} not divisible by pp {self._pp}"
                    " (slots are the GPipe microbatch axis)"
                )

        # sequence parallelism: prefill chunks ring-attend over sp
        # (models/llama.py forward_chunk_sp); decode is a single position
        # per lane, which sp neither helps nor hinders
        self._sp = (
            mesh.shape[AXIS_SP]
            if mesh is not None and AXIS_SP in mesh.axis_names
            else 1
        )
        if self._sp > 1:
            if ec.prefill_chunk % self._sp:
                raise ValueError(
                    f"prefill_chunk {ec.prefill_chunk} not divisible by sp "
                    f"{self._sp} (the chunk's sequence axis shards over sp)"
                )
            if self._pp > 1:
                raise ValueError("pp and sp cannot be combined yet")

    def _put(self, host_arr) -> jax.Array:
        """Host array → device array usable by the step fns. On a
        process-spanning mesh this builds a REPLICATED global array (every
        process holds the full value — the multihost lockstep contract);
        otherwise a plain transfer."""
        a = np.asarray(host_arr)
        if not self._multihost:
            return jnp.asarray(a)
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.make_array_from_callback(
            a.shape, NamedSharding(self.mesh, PartitionSpec()),
            lambda idx: a[idx],
        )

    # -- jitted step functions ----------------------------------------------

    def _build_decode_fn(self, with_lp: bool = False, with_pen: bool = False,
                         with_sample: bool = True):
        cfg = self.model_config
        k_steps = self.config.decode_steps
        max_pos = self.config.max_model_len - 1
        n_top = self.config.top_logprobs
        dense = self._decode_dense
        # output watchdog (docs/resilience.md §Silent corruption): engine-
        # wide constant, so the variant cache key is unchanged. When on, the
        # fn takes one extra scalar (``wdf``: the poison-drill flag) and
        # substitutes WATCHDOG_TOKEN for any lane whose logits are
        # non-finite or exploding — the host loop detects the tripped lane
        # from the fetched tokens alone, zero extra outputs or transfers.
        wd = self._watchdog
        wd_limit = self._integrity.logit_limit if wd else 0.0

        def _wd_bad(sel, wdf):
            # full_like keeps sel's dtype exactly: the watchdog must not
            # perturb the sampling math of a healthy dispatch in any way
            sel = jnp.where(wdf > 0, jnp.full_like(sel, jnp.nan), sel)
            bad = (~jnp.all(jnp.isfinite(sel), axis=-1)) | (
                jnp.max(jnp.abs(sel), axis=-1) > wd_limit
            )
            return sel, bad

        def decode(params, cache, counts, tokens, positions, tables, step_ctr,
                   ipack, fpack, wdf=None):
            # ipack [2,S] int32 = (seeds, topk); fpack [4,S] f32 =
            # (temp, topp, freqp, presp). Packed so a dispatch uploads at
            # most two small host arrays (each upload is a fixed-latency
            # transfer on a tunneled chip), cached by _DevMirror.
            # step_ctr: replicated int32 scalar; the step key derives from it
            # IN-JIT so multihost lockstep needs only a number on the wire.
            step_key = jax.random.fold_in(jax.random.PRNGKey(0), step_ctr)
            seeds, topk = ipack[0], ipack[1]
            temp, topp, freqp, presp = fpack[0], fpack[1], fpack[2], fpack[3]
            # tokens/positions: [S]; tables: [S, MB]. Scans k_steps forward+
            # sample iterations, feeding each sampled token back in — one
            # dispatch yields [S, k_steps] tokens. The final carry (tokens,
            # positions) is returned so the NEXT dispatch can chain off the
            # device-resident state without a host round trip (pipelined
            # decode); a lane whose position would pass max_pos goes to -1 so
            # speculative steps never scatter into a block past its table.
            # The penalty-count buffer rides the same carry, so within-chunk
            # repeats are penalized too.
            #
            # The decode scan is windowed in BOTH attention tiers: the pool is
            # READ-ONLY inside the scan; each step's K/V go to a [L, S, W]
            # window buffer riding the carry (models/llama.py forward_window),
            # flushed to pages in ONE scatter per dispatch — per-step pool
            # scatters cost more than the step's whole matmul work on TPU.
            # Only the history read differs (ops/attention.py
            # decode_uses_pallas): the jnp tier pre-gathers pages to a dense
            # buffer once per dispatch (per-step gathers lower to serialized
            # page slices); the kernel tier streams pages HBM→VMEM in the
            # Pallas kernel and merges the window partial flash-decoding
            # style via the kernel's softmax stats.
            if self._pp > 1:
                # pipeline decode: each step is a pipelined single-token
                # forward; the cache rides the scan carry (pages stay on
                # their stage's shard, written by decoder_layer per step).
                # The window structure is not used — GPipe's microbatch
                # schedule already amortizes the per-layer cost, and pages
                # are written stage-locally with no cross-stage scatter.
                from dynamo_tpu.parallel.pipeline import pipeline_forward

                def body_pp(carry, k):
                    toks, pos, cache, counts = carry
                    logits, cache = pipeline_forward(
                        params, cfg, toks[:, None], pos[:, None], cache,
                        tables, self.mesh,
                    )
                    if with_sample:
                        kk = jax.random.fold_in(step_key, k)
                        keys = jax.vmap(lambda s: jax.random.fold_in(kk, s))(seeds)
                    else:
                        keys = None
                    sel = logits[:, 0]
                    if wd:
                        sel, bad = _wd_bad(sel, wdf)
                    sampled_from = (
                        apply_penalties(sel, counts, freqp, presp)
                        if with_pen else sel
                    )
                    nxt = sample_tokens(sampled_from, keys, temp, topk, topp,
                                        greedy_only=not with_sample)
                    if wd:
                        nxt = jnp.where(
                            bad & (pos >= 0),
                            jnp.int32(WATCHDOG_TOKEN), nxt,
                        )
                    if with_pen:
                        counts = update_counts(counts, nxt, pos >= 0)
                    new_pos = jnp.where((pos >= 0) & (pos < max_pos), pos + 1, -1)
                    if with_lp:
                        lp, tids, tlps = token_logprobs(sel, nxt, n_top)
                        return (nxt, new_pos, cache, counts), (nxt, lp, tids, tlps)
                    return (nxt, new_pos, cache, counts), nxt

                (toks, pos, cache, counts), out = jax.lax.scan(
                    body_pp, (tokens, positions, cache, counts),
                    jnp.arange(k_steps),
                )
                if with_lp:
                    out, lps, tids, tlps = out
                    return (
                        out.T, lps.T, tids.transpose(1, 0, 2),
                        tlps.transpose(1, 0, 2), toks, pos, cache, counts,
                    )
                return out.T, toks, pos, cache, counts

            base = positions
            wshape = (
                cfg.num_layers, self.config.max_slots, k_steps,
                cfg.num_kv_heads, cfg.head_dim,
            )
            # window buffers hold COMPUTE-dtype values even over an int8
            # pool (they are attended directly; flush_window quantizes them
            # on the way into the pages)
            wk0 = jnp.zeros(wshape, self._compute_dtype)
            wv0 = jnp.zeros(wshape, self._compute_dtype)
            if dense:
                hist_k, hist_v = gather_history(
                    cache, tables, out_dtype=self._compute_dtype
                )
                history = ("dense", hist_k, hist_v)
            else:
                interpret = jax.devices()[0].platform == "cpu"
                history = ("paged", cache, tables, self.mesh, interpret)

            def body(carry, k):
                toks, pos, counts, wk, wv = carry
                sel, wk, wv = forward_window(
                    params, cfg, toks, pos, history, base, wk, wv, k,
                )
                if wd:
                    sel, bad = _wd_bad(sel, wdf)
                if with_sample:
                    kk = jax.random.fold_in(step_key, k)
                    keys = jax.vmap(lambda s: jax.random.fold_in(kk, s))(seeds)
                else:
                    keys = None  # unused by the greedy-only sampler
                sampled_from = (
                    apply_penalties(sel, counts, freqp, presp)
                    if with_pen else sel
                )
                nxt = sample_tokens(sampled_from, keys, temp, topk, topp,
                                    greedy_only=not with_sample)
                if wd:
                    nxt = jnp.where(
                        bad & (pos >= 0), jnp.int32(WATCHDOG_TOKEN), nxt
                    )
                if with_pen:
                    counts = update_counts(counts, nxt, pos >= 0)
                new_pos = jnp.where((pos >= 0) & (pos < max_pos), pos + 1, -1)
                if with_lp:
                    lp, tids, tlps = token_logprobs(sel, nxt, n_top)
                    return (nxt, new_pos, counts, wk, wv), (nxt, lp, tids, tlps)
                return (nxt, new_pos, counts, wk, wv), nxt

            (toks, pos, counts, wk, wv), out = jax.lax.scan(
                body, (tokens, positions, counts, wk0, wv0),
                jnp.arange(k_steps),
            )
            cache = flush_window(cache, tables, base, wk, wv, max_pos)
            # outputs are scan-stacked [k_steps, S, ...] → slot-major
            if with_lp:
                out, lps, tids, tlps = out
                return (
                    out.T, lps.T, tids.transpose(1, 0, 2),
                    tlps.transpose(1, 0, 2), toks, pos, cache, counts,
                )
            return out.T, toks, pos, cache, counts

        if self._multihost:
            # leader must device_get sampled tokens/carries: pin every output
            # except the cache to a replicated sharding (tiny all-gathers)
            rep, cache_sh = self._io_shardings()
            n_extra = 6 if with_lp else 3
            out_sh = (rep,) * n_extra + ({"k": cache_sh, "v": cache_sh}, rep)
            return jax.jit(decode, donate_argnums=(1, 2), out_shardings=out_sh)
        return jax.jit(decode, donate_argnums=(1, 2))

    def _io_shardings(self):
        from jax.sharding import NamedSharding, PartitionSpec

        from dynamo_tpu.parallel.mesh import kv_cache_sharding

        return NamedSharding(self.mesh, PartitionSpec()), kv_cache_sharding(self.mesh)

    def _decode(self, want_lp: bool, want_pen: bool = False,
                want_sample: bool = True):
        """The decode variant with/without logprobs/penalties/sampling (each
        compiled lazily: the logprobs math + its device→host transfer, the
        penalty-count scatter, and the top-k/categorical sampling block stay
        off the hot path when no live lane asked for them)."""
        key = (want_lp, want_pen, want_sample)
        fn = self._decode_fns.get(key)
        if fn is None:
            record_compile("decode", detail=(
                f"lp={want_lp} pen={want_pen} sample={want_sample} "
                f"[S={self.config.max_slots},k={self.config.decode_steps}]"
            ))
            fn = self._decode_fns[key] = self._build_decode_fn(
                want_lp, want_pen, want_sample
            )
        return fn

    def _chunk(self, want_lp: bool, want_pen: bool = False,
               want_sample: bool = True, want_history: bool = True):
        if self._pp > 1 or self._sp > 1:
            want_history = True  # pp/sp forwards have no history-free variant
        key = (want_lp, want_pen, want_sample, want_history)
        fn = self._chunk_fns.get(key)
        if fn is None:
            record_compile("chunk", detail=(
                f"lp={want_lp} pen={want_pen} sample={want_sample} "
                f"history={want_history} [S={self.config.max_slots},"
                f"C={self.config.prefill_chunk}]"
            ))
            fn = self._chunk_fns[key] = self._build_chunk_fn(
                want_lp, want_pen, want_sample, want_history
            )
        return fn

    def _build_chunk_fn(self, with_lp: bool = False, with_pen: bool = False,
                        with_sample: bool = True, with_history: bool = True):
        cfg = self.model_config
        S = self.config.max_slots
        n_top = self.config.top_logprobs
        wd = self._watchdog
        wd_limit = self._integrity.logit_limit if wd else 0.0

        def chunk(params, cache, counts, tokens, positions, tables, sample_at,
                  step_ctr, ipack, fpack, wdf=None):
            step_key = jax.random.fold_in(jax.random.PRNGKey(0), step_ctr)
            seeds, topk = ipack[0], ipack[1]
            temp, topp, freqp, presp = fpack[0], fpack[1], fpack[2], fpack[3]
            # tokens/positions: [S, C] (−1 positions = padding); sample_at: [S]
            # index of the token whose logits to sample, −1 → output unused.
            # One shape serves any mix of prefilling and decoding lanes.
            # The LM head runs on the gathered [S, E] sample positions only —
            # never on the full [S, C, E] chunk (at C=128 that head matmul and
            # its [S, C, vocab] float32 logits dwarf the useful work and sat
            # directly on the TTFT critical path).
            if self._pp > 1:
                from dynamo_tpu.parallel.pipeline import pipeline_forward

                h, cache = pipeline_forward(
                    params, cfg, tokens, positions, cache, tables, self.mesh,
                    hidden_only=True,
                )
            elif self._sp > 1:
                from dynamo_tpu.models.llama import forward_chunk_sp

                h, cache = forward_chunk_sp(
                    params, cfg, tokens, positions, cache, tables, self.mesh,
                    hidden_only=True,
                )
            else:
                # history/fresh split (models/llama.py forward_chunk): the
                # page scatter runs off the attention critical path instead
                # of serializing scatter -> gather -> einsum per layer
                h, cache = forward_chunk(
                    params, cfg, tokens, positions, cache, tables,
                    hidden_only=True, with_history=with_history,
                )
            hs = h[jnp.arange(S), jnp.clip(sample_at, 0)]  # [S, E]
            sel = lm_head(params, cfg, hs)  # [S, V]
            if wd:
                # output watchdog: poison-drill substitution + per-lane
                # non-finite/exploding flag → WATCHDOG_TOKEN sentinel
                sel = jnp.where(wdf > 0, jnp.full_like(sel, jnp.nan), sel)
                bad = (~jnp.all(jnp.isfinite(sel), axis=-1)) | (
                    jnp.max(jnp.abs(sel), axis=-1) > wd_limit
                )
            if with_sample:
                keys = jax.vmap(lambda s: jax.random.fold_in(step_key, s))(seeds)
            else:
                keys = None
            sampled_from = (
                apply_penalties(sel, counts, freqp, presp) if with_pen else sel
            )
            nxt = sample_tokens(sampled_from, keys, temp, topk, topp,
                                greedy_only=not with_sample)
            if wd:
                nxt = jnp.where(
                    bad & (sample_at >= 0), jnp.int32(WATCHDOG_TOKEN), nxt
                )
            if with_pen:
                counts = update_counts(counts, nxt, sample_at >= 0)
            if with_lp:
                lp, tids, tlps = token_logprobs(sel, nxt, n_top)
                return nxt, lp, tids, tlps, cache, counts
            return nxt, cache, counts

        if self._multihost:
            rep, cache_sh = self._io_shardings()
            n_extra = 4 if with_lp else 1
            out_sh = (rep,) * n_extra + ({"k": cache_sh, "v": cache_sh}, rep)
            return jax.jit(chunk, donate_argnums=(1, 2), out_shardings=out_sh)
        return jax.jit(chunk, donate_argnums=(1, 2))

    def _verify(self, want_lp: bool, want_pen: bool = False,
                want_sample: bool = True):
        """The speculative-verify variant (drafted tokens scored in one
        weight stream; engine_jax/drafter.py). Compiled lazily like the
        decode/chunk variants — and never at all while spec_k == 0."""
        key = (want_lp, want_pen, want_sample)
        fn = self._verify_fns.get(key)
        if fn is None:
            record_compile("verify", detail=(
                f"lp={want_lp} pen={want_pen} sample={want_sample} "
                f"[S={self.config.max_slots},k1={self._spec_k + 1}]"
            ))
            fn = self._verify_fns[key] = self._build_verify_fn(
                want_lp, want_pen, want_sample
            )
        return fn

    def _build_verify_fn(self, with_lp: bool = False, with_pen: bool = False,
                         with_sample: bool = True):
        """One speculative-verify dispatch: feed ``[last_token, draft_0, ..,
        draft_{k-1}]`` per lane ([S, K1] with -1-position padding), compute
        logits at EVERY fed position in one forward pass, and sample the
        engine's own target token per position (sampling.speculative_targets
        — the point-mass rejection-sampling rule). The host keeps the
        drafted prefix that matches the targets plus the first non-matching
        target as the bonus token, so one weight stream emits up to k+1
        tokens. Unlike the chunk fn, the LM head runs on all K1 positions —
        at K1 ≤ MAX_SPEC_K+1 that head matmul is the price of admission for
        the amortized stream, and it is a fraction of the full chunk head
        this path replaces."""
        cfg = self.model_config
        n_top = self.config.top_logprobs
        wd = self._watchdog
        wd_limit = self._integrity.logit_limit if wd else 0.0

        def verify(params, cache, counts, tokens, positions, tables, step_ctr,
                   ipack, fpack, wdf=None):
            step_key = jax.random.fold_in(jax.random.PRNGKey(0), step_ctr)
            seeds, topk = ipack[0], ipack[1]
            temp, topp, freqp, presp = fpack[0], fpack[1], fpack[2], fpack[3]
            # KV for every fed position is written by the forward pass;
            # positions past the accepted prefix hold garbage that later
            # dispatches overwrite before any mask lets it be attended
            # (history masks are position-based: pool reads stop below each
            # lane's current position).
            h, cache = forward_chunk(
                params, cfg, tokens, positions, cache, tables,
                hidden_only=True, with_history=True,
            )
            logits_all = lm_head(params, cfg, h)  # [S, K1, V] f32
            if wd:
                logits_all = jnp.where(
                    wdf > 0, jnp.full_like(logits_all, jnp.nan), logits_all
                )
                bad_pos = (~jnp.all(jnp.isfinite(logits_all), axis=-1)) | (
                    jnp.max(jnp.abs(logits_all), axis=-1) > wd_limit
                )  # [S, K1]
                bad = jnp.any(bad_pos & (positions >= 0), axis=-1)  # [S]
            outs = speculative_targets(
                logits_all, counts, positions >= 0, step_key, seeds,
                temp, topk, topp, freqp, presp,
                with_pen=with_pen, with_sample=with_sample, with_lp=with_lp,
                n_top=n_top,
            )
            if with_lp:
                tgt, lp, tids, tlps, counts = outs
                if wd:
                    tgt = jnp.where(
                        bad[:, None], jnp.int32(WATCHDOG_TOKEN), tgt
                    )
                return tgt, lp, tids, tlps, cache, counts
            tgt, counts = outs
            if wd:
                tgt = jnp.where(bad[:, None], jnp.int32(WATCHDOG_TOKEN), tgt)
            return tgt, cache, counts

        return jax.jit(verify, donate_argnums=(1, 2))

    # -- penalty-count buffer -------------------------------------------------

    def _slow_fault(self) -> None:
        """The ``slow`` fault action at the engine dispatch point
        (docs/resilience.md §Fail-slow): an injected host-side delay —
        fixed + seeded jitter — right before the jitted call, modelling a
        worker that passes every probe but drags every dispatch (thermal
        throttle, sick NIC, noisy co-tenant). Deliberately independent of
        the straggler/profiling knobs: the chaos gate's *undefended*
        control leg needs the fault to fire with the defense off."""
        if faults_mod.current() is not None:
            d = faults_mod.slow_gate("engine", self._fault_addr)
            if d > 0:
                time.sleep(d)

    def _straggler_tick(self, phase: str, t_step: float, tokens: int) -> None:
        """One dispatch into the fail-slow detector: coarse step-loop wall
        time per token (fed EVERY dispatch, unlike the sampled profiling
        timeline — a differential verdict over peers needs the full
        stream, and two perf_counter reads per dispatch are cheap)."""
        self._straggler.note_dispatch(
            phase, (time.perf_counter() - t_step) * 1e6, tokens
        )

    def _wd_args(self) -> tuple:
        """Extra dispatch args for the output watchdog: empty with the
        integrity plane off (the jitted programs then take exactly the
        pre-integrity signature), else one scalar — 0 normally, 1 when the
        ``poison`` fault action fires for this dispatch (the injected-SDC
        drill: the fn overwrites its logits with NaN in-jit, and the
        watchdog must catch every affected lane before a token escapes).
        The steady-state 0 is uploaded ONCE and reused — on a tunneled
        chip every fresh upload is a fixed-latency transfer, and the hot
        path must not pay one per dispatch for a drill flag."""
        if not self._watchdog:
            return ()
        if faults_mod.current() is not None and faults_mod.poison_gate(
            "engine", self._fault_addr
        ):
            return (self._put(np.int32(1)),)
        wd0 = getattr(self, "_wd_zero", None)
        if wd0 is None:
            wd0 = self._wd_zero = self._put(np.int32(0))
        return (wd0,)

    def _counts_sync_fn(self, rbucket: int, pbucket: int):
        """Tiny jitted reset+rebuild of penalty-count rows. Bucketed shapes
        (powers of two) bound the number of compilations; padded entries use
        row index S, dropped by the scatters."""
        fn = self._counts_sync_fns.get((rbucket, pbucket))
        if fn is None:
            record_compile("counts_sync")

            def sync(counts, reset_rows, add_rows, add_toks):
                counts = counts.at[reset_rows].set(0, mode="drop")
                return counts.at[add_rows, add_toks].add(1, mode="drop")

            fn = self._counts_sync_fns[(rbucket, pbucket)] = jax.jit(
                sync, donate_argnums=(0,)
            )
        return fn

    def _counts_fix_fn(self, pbucket: int):
        """Tiny jitted subtraction of over-added penalty counts. The verify
        scan adds EVERY active position's target into the count buffer
        (sequential exactness up to the first draft mismatch costs pollution
        past it); the host knows exactly which targets were kept, so the
        correction is ≤ spec_k entries per lane per dispatch — never a full
        out_tokens rebuild. Padded entries use row index S, dropped."""
        fn = self._counts_fix_fns.get(pbucket)
        if fn is None:
            record_compile("counts_fix")

            def fix(counts, rows, toks):
                return counts.at[rows, toks].add(-1, mode="drop")

            fn = self._counts_fix_fns[pbucket] = jax.jit(
                fix, donate_argnums=(0,)
            )
        return fn

    def _release_counts(self) -> None:
        """No penalized lane is running: free the [S, V] device buffer and
        the strong _Seq references held by the row tracking. Rebuilt from
        out_tokens on the next penalized admission. The multihost leader
        broadcasts the release — followers drop theirs on non-penalized
        dispatches, but an IDLE engine sends no dispatches, and without the
        marker each follower would hold the buffer until unrelated traffic
        arrived."""
        if self._counts is not None:
            self._counts = None
            self._counts_lanes = [None] * self.config.max_slots
            if self._dispatch_hook is not None:
                self._dispatch_hook("counts_release", {}, {})

    def _sync_counts(self, lanes: List[Optional["_Seq"]]) -> None:
        """Bring the device count buffer in line with the current lane set:
        rows whose sequence changed since the last penalized dispatch are
        zeroed and rebuilt from that sequence's emitted output tokens (so
        penalties survive preemption and remote prefill). Rows whose lane is
        unchanged were maintained in-jit and are left alone. Rows of
        NON-penalized lanes are skipped entirely — apply_penalties multiplies
        them by zero, so their contents are never read, and rebuilding them
        (potentially thousands of out_tokens across a busy engine) would
        stall every lane the moment the first penalized request lands."""
        S = self.config.max_slots
        if self._counts is None:
            # _put: replicated global array on a process-spanning mesh
            self._counts = self._put(
                np.zeros((S, self.model_config.vocab_size), np.int32)
            )
        changed = [
            i for i in range(S)
            if self._counts_lanes[i] is not lanes[i]
            and lanes[i] is not None and lanes[i].penalized
        ]
        if not changed:
            self._counts_lanes = list(lanes)
            return
        pairs: List[Tuple[int, int]] = []
        for i in changed:
            seq = lanes[i]
            if seq.out_tokens:
                pairs.extend((i, t) for t in seq.out_tokens)
        rb, pb = 1, 1
        while rb < len(changed):
            rb *= 2
        while pb < max(len(pairs), 1):
            pb *= 2
        reset = np.full((rb,), S, np.int32)
        reset[: len(changed)] = changed
        add_rows = np.full((pb,), S, np.int32)
        add_toks = np.zeros((pb,), np.int32)
        for j, (r, t) in enumerate(pairs):
            add_rows[j] = r
            add_toks[j] = t
        if self._dispatch_hook is not None:
            # the sync is itself a device program: followers must run it in
            # the same order as every other dispatch
            self._dispatch_hook(
                "counts", dict(rb=rb, pb=pb),
                dict(reset=reset, add_rows=add_rows, add_toks=add_toks),
            )
        self._counts = self._counts_sync_fn(rb, pb)(
            self._counts, self._put(reset), self._put(add_rows),
            self._put(add_toks),
        )
        self._counts_lanes = list(lanes)

    def warmup(self, variants: str = "all") -> Dict[str, float]:
        """Compile the chunk and decode step functions before serving traffic.

        A cold compile is tens of seconds on a real chip — taken mid-request it
        stalls every in-flight sequence (the round-1 bench measured a 13.5 s
        head-of-line compile inside the timed run).

        Single-chip engines compile AOT (``jit.lower(shapes).compile()``)
        over abstract shapes — nothing executes, so no donation hazard — and
        the variants compile CONCURRENTLY in a thread pool (XLA releases the
        GIL), cutting first-boot wall time to roughly the slowest single
        program. ``variants="greedy"`` compiles only the three
        greedy-serving programs (big-model boots where every extra program
        costs minutes through a remote compiler); the lp/pen variants stay
        lazy in every mode (rare; first use compiles once).

        Mesh engines keep the executing warmup: AOT avals would need the
        exact input shardings, and on a multi-process mesh the warmup
        executions themselves must run in leader/follower lockstep.
        Returns per-variant compile seconds (recorded by the bench —
        VERDICT r4 item 9)."""
        cfg = self.config
        S, C, MB = cfg.max_slots, cfg.prefill_chunk, cfg.max_blocks_per_seq
        timings: Dict[str, float] = {}
        sample_set = (False,) if variants == "greedy" else (False, True)

        if self.mesh is not None:
            neg = np.full((S, C), -1, np.int32)
            zeros_sc = np.zeros((S, C), np.int32)
            tables = np.zeros((S, MB), np.int32)
            svec_i = np.zeros((S,), np.int32)
            svec_f = np.zeros((S,), np.float32)
            ones_f = np.ones((S,), np.float32)
            ctr = self._put(np.int32(0))
            ipack = self._put(np.stack([svec_i, svec_i]))
            fpack = self._put(np.stack([svec_f, ones_f, svec_f, svec_f]))
            for want_sample in sample_set:
                for want_history in (False, True):
                    t0 = time.perf_counter()
                    out, self.cache, self._dummy_counts = self._chunk(
                        False, False, want_sample, want_history
                    )(
                        self.params, self.cache, self._dummy_counts,
                        self._put(zeros_sc), self._put(neg), self._put(tables),
                        self._put(np.full((S,), -1, np.int32)), ctr,
                        ipack, fpack,
                    )
                    # dynlint: allow-host-sync(warmup compile barrier, pre-serving)
                    jax.device_get(out)
                    timings[
                        f"chunk(sample={want_sample},history={want_history})"
                    ] = round(time.perf_counter() - t0, 2)
                t0 = time.perf_counter()
                out, _, _, self.cache, self._dummy_counts = self._decode(
                    False, False, want_sample
                )(
                    self.params_decode, self.cache, self._dummy_counts,
                    self._put(svec_i), self._put(np.full((S,), -1, np.int32)),
                    self._put(tables), ctr, ipack, fpack,
                )
                # dynlint: allow-host-sync(warmup compile barrier, pre-serving)
                jax.device_get(out)
                timings[f"decode(sample={want_sample})"] = round(
                    time.perf_counter() - t0, 2
                )
            return timings

        from concurrent.futures import ThreadPoolExecutor

        def sd(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype)

        p_sd = jax.tree.map(lambda a: sd(a.shape, a.dtype), self.params)
        pd_sd = jax.tree.map(
            lambda a: sd(a.shape, a.dtype), self.params_decode
        )
        cache_sd = jax.tree.map(lambda a: sd(a.shape, a.dtype), self.cache)
        counts_sd = jax.tree.map(
            lambda a: sd(a.shape, a.dtype), self._dummy_counts
        )
        tbl = sd((S, MB), jnp.int32)
        ctr = sd((), jnp.int32)
        ip = sd((2, S), jnp.int32)
        fp = sd((4, S), jnp.float32)
        svec = sd((S,), jnp.int32)
        # watchdog variants take one extra scalar (the poison flag)
        wd_tail = (sd((), jnp.int32),) if self._watchdog else ()

        jobs = []
        for want_sample in sample_set:
            for want_history in (False, True):
                jobs.append((
                    f"chunk(sample={want_sample},history={want_history})",
                    self._chunk(False, False, want_sample, want_history),
                    (p_sd, cache_sd, counts_sd, sd((S, C), jnp.int32),
                     sd((S, C), jnp.int32), tbl, svec, ctr, ip, fp) + wd_tail,
                    ("chunk", False, False, want_sample, want_history),
                ))
            jobs.append((
                f"decode(sample={want_sample})",
                self._decode(False, False, want_sample),
                (pd_sd, cache_sd, counts_sd, svec, svec, tbl, ctr, ip, fp)
                + wd_tail,
                ("decode", False, False, want_sample),
            ))
            if self._spec_k > 0:
                sk1 = sd((S, self._spec_k + 1), jnp.int32)
                jobs.append((
                    f"verify(sample={want_sample})",
                    self._verify(False, False, want_sample),
                    (pd_sd, cache_sd, counts_sd, sk1, sk1, tbl, ctr, ip, fp)
                    + wd_tail,
                    ("verify", False, False, want_sample),
                ))

        def compile_one(job):
            name, fn, args, key = job
            if not hasattr(fn, "lower"):  # already a compiled executable
                return key, fn
            t0 = time.perf_counter()
            compiled = fn.lower(*args).compile()
            timings[name] = round(time.perf_counter() - t0, 2)
            return key, compiled

        with ThreadPoolExecutor(max_workers=min(6, len(jobs))) as ex:
            for key, compiled in ex.map(compile_one, jobs):
                # serve straight off the compiled executable
                if key[0] == "chunk":
                    self._chunk_fns[key[1:]] = compiled
                elif key[0] == "verify":
                    self._verify_fns[key[1:]] = compiled
                else:
                    self._decode_fns[key[1:]] = compiled
        return timings

    # -- AsyncEngine interface ----------------------------------------------

    async def generate(
        self, request: Context[PreprocessedRequest]
    ) -> AsyncIterator[Annotated[dict]]:
        req = request.data
        if not isinstance(req, PreprocessedRequest):
            req = PreprocessedRequest.from_dict(req)
        if len(req.token_ids) > self.config.max_model_len - 1:
            yield Annotated.from_error(
                f"prompt is {len(req.token_ids)} tokens; engine max_model_len "
                f"is {self.config.max_model_len}"
            )
            return
        self._ensure_thread()
        seq = _Seq(request, req, asyncio.get_running_loop())
        if seq.resumed:
            self.resumed_requests += 1
        tenant = getattr(request.context, "tenant", None)
        if self._qos is not None:
            # QoS on: anonymous requests become the shared default tenant
            # (they must not bypass fair queuing / budgets); the class
            # table supplies the eviction level + scheduling weight
            seq.tenant = tenant or qos_mod.DEFAULT_TENANT
            seq.level, seq.weight = self._qos.class_of(seq.tenant)
        elif tenant:
            seq.tenant = tenant  # attribution only (spans, metrics)
        if self._spec_k > 0 and not self._multihost:
            # one suffix index per request (prompt indexed up front, emitted
            # tokens appended as they stream); spec off ⇒ stays None and the
            # step loop never allocates drafter state. Multihost never
            # dispatches verify (followers only replay chunk/decode
            # opcodes), so it must not pay the index either.
            seq.drafter = NgramDrafter(
                seq.prompt, self._spec_k, self._spec_ngram
            )
        with self._cond:
            self._pending.append(seq)
            self._cond.notify()

        try:
            while True:
                item = await seq.out_queue.get()
                if item is _FINISHED:
                    return
                yield item
        finally:
            # Consumer closed the stream (stop string hit downstream, client
            # disconnect, GeneratorExit): make sure the engine stops burning
            # the slot. No-op after a normal finish.
            request.context.stop_generating()
            with self._cond:
                self._cond.notify()

    # -- engine thread -------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._step_loop, name="jax-engine-step", daemon=True
            )
            self._thread.start()

    def close(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _step_loop(self) -> None:
        try:
            while True:
                with self._cond:
                    while (
                        not self._shutdown
                        and not self._pending
                        and not self._posted
                        and not any(self._slots)
                        and self._inflight is None
                        and not self._pending_spills
                        and self._counts is None  # idle pass frees it first
                    ):
                        if self._awaiting or self._staged_migrations:
                            # wake periodically to sweep remote-prefill
                            # timeouts and unclaimed staged migrations
                            self._cond.wait(timeout=1.0)
                            break
                        # parking idle: record it, or the last busy beat
                        # would age into a false stall (health.py reads
                        # busy-at-last-beat, and an idle park beats no more)
                        self.heartbeat.beat(busy=False)
                        self._cond.wait()
                    if self._shutdown:
                        # drain posted callbacks before exiting: callers of
                        # post() (transfer-plane _engine_call) await futures
                        # these resolve — dropping them would hang the
                        # awaiting task forever on a close() race
                        self._run_posted()
                        return
                # liveness beat BEFORE the work: if the dispatch below (or a
                # posted callback / spill harvest) wedges, the recorded busy
                # flag plus a growing beat age is exactly the stall
                # signature the health monitor detects. Every wake source of
                # the idle-wait predicate above counts as busy — a wedge in
                # a posted callback on an otherwise-idle engine must not
                # masquerade as an idle park.
                self.heartbeat.beat(busy=bool(
                    self._pending
                    or self._posted
                    or self._inflight is not None
                    or any(s is not None for s in self._slots)
                    or self._awaiting
                    or self._pending_spills
                ))
                self._run_posted()
                self._sweep_remote_timeouts()
                self._sweep_staged()
                idle = (
                    not self._pending and not any(self._slots)
                    and self._inflight is None
                )
                # idle = nothing to stall: drain spills fully so revisits
                # after an idle gap see their prefixes in the host tier,
                # and drop the [S, V] penalty-count buffer (16 MB at a
                # 128k vocab) a final dispatch with penalized lanes left
                # allocated — no later dispatch would ever release it
                self._harvest_spills(force=idle)
                if idle:
                    self._release_counts()
                    if self._perf is not None:
                        # exclude the idle gap from throughput timing
                        self._perf.note_idle()
                    if self._fair is not None:
                        # bound fair-queue memory across tenant churn; an
                        # idle engine has no backlog to be fair about
                        self._fair.forget_absent(
                            [s.tenant for s in self._awaiting.values()]
                        )
                self._coalesce_admission_wave()
                self._admit()
                self._dispatch_step()
                if (
                    not any(self._slots) and self._inflight is None
                    and self._pending and self._awaiting
                ):
                    # every pending request is parked (capacity or shared
                    # in-flight prefix) behind remote prefills: poll gently
                    # instead of spinning the GIL against the transfer plane
                    with self._cond:
                        self._cond.wait(timeout=0.005)
        except Exception:
            logger.exception("engine step loop crashed")
            # fail every in-flight request rather than hanging clients
            for seq in list(self._slots) + list(self._pending) + list(self._awaiting.values()):
                if seq is not None:
                    seq.emit(Annotated.from_error("engine internal error"))
                    seq.emit(_FINISHED)

    def post(self, fn) -> None:
        """Schedule a host function to run on the engine thread (thread-safe).
        The only way external code may touch the cache or allocator. After
        close(), the fn runs INLINE on the caller thread: the step thread's
        shutdown drain only covers callbacks it observed, and a post racing
        the drain would otherwise never run — hanging any _engine_call
        future awaiting it."""
        with self._cond:
            if not self._shutdown:
                self._ensure_thread()
                self._posted.append(fn)
                self._cond.notify()
                return
        # inline path: serialize against the step thread's shutdown drain and
        # any other post-close caller — two teardown threads (e.g. concurrent
        # transfer-plane _engine_calls) must not mutate allocator/cache state
        # concurrently when the engine thread no longer serializes them
        with self._posted_exec_lock:
            fn()

    def _run_posted(self) -> None:
        while True:
            with self._cond:
                if not self._posted:
                    return
                fn = self._posted.popleft()
            with self._posted_exec_lock:
                fn()

    # -- scheduling ----------------------------------------------------------

    def _coalesce_admission_wave(self) -> None:
        """Hold the first dispatch briefly while an admission wave is still
        landing (engine idle, pending requests growing, free slots left), so
        the whole wave prefills together. Without this, whichever requests
        happen to be queued when the engine thread first wakes prefill alone
        and every straggler's TTFT grows by a full extra chunk dispatch."""
        window = self.config.admission_window
        if window <= 0:
            return
        if self._inflight is not None or any(s is not None for s in self._slots):
            return  # engine busy: dispatch cadence already set by compute
        deadline = time.perf_counter() + window
        with self._cond:
            prev = len(self._pending)
            while (
                0 < prev < self.config.max_slots
                and not self._shutdown
                and time.perf_counter() < deadline
            ):
                self._cond.wait(timeout=0.001)
                if len(self._pending) == prev:
                    return  # wave stopped growing
                prev = len(self._pending)

    def _admit(self) -> None:
        """Move pending requests into free slots; run their prefill."""
        deferred: List[_Seq] = []  # waiting on another lane's in-flight prefix
        try:
            self._admit_inner(deferred)
        finally:
            if deferred:
                with self._cond:
                    for s in reversed(deferred):
                        self._pending.appendleft(s)

    def _pop_pending_locked(self) -> "_Seq":
        """Next pending request to consider. FIFO on the single-tenant
        path; with QoS on, weighted-fair: the request whose tenant has
        the smallest virtual time (most starved by weighted share) wins,
        FIFO within a tenant — a noisy neighbor's deep backlog cannot
        starve a light tenant's next request. Caller holds ``_cond``."""
        if self._fair is None or len(self._pending) <= 1:
            return self._pending.popleft()
        i = self._fair.pick([s.tenant for s in self._pending])
        if i == 0:
            return self._pending.popleft()
        seq = self._pending[i]
        del self._pending[i]
        return seq

    def _tenant_contended(self, tenant: str) -> bool:
        """Is any OTHER tenant actively HOLDING engine resources (a slot
        or a remote-prefill allocation)? KV budgets are work-conserving:
        they bind only under contention — a tenant alone on the chip may
        use the whole pool. Deliberately NOT counting merely-pending
        tenants: two over-budget tenants whose only contention is each
        other's queued request would otherwise defer each other forever
        on an empty engine (each admits here; the class-aware preemption
        path still reclaims from whichever overruns once both run)."""
        if any(
            s is not None and s.tenant != tenant for s in self._slots
        ):
            return True
        return any(s.tenant != tenant for s in self._awaiting.values())

    def _kv_budget_defers(self, seq: "_Seq") -> bool:
        """Admission-side KV budget: defer a tenant already holding (or
        about to exceed) its pool share while other tenants are active."""
        if self._tenant_kv_budget <= 0 or not seq.tenant:
            return False
        need = self.allocator.blocks_needed(len(seq.prompt))
        held = self.allocator.tenant_blocks.get(seq.tenant, 0)
        if held + need <= self._tenant_kv_budget:
            return False
        return self._tenant_contended(seq.tenant)

    def _slot_budget_defers(self, seq: "_Seq") -> bool:
        """Admission-side slot budget (docs/qos.md): a tenant already
        occupying its share of the decode batch defers while any OTHER
        tenant is actively holding resources — concurrency isolation with
        the same work-conserving contract as the KV budget (an uncontended
        tenant may fill every slot)."""
        if self._tenant_slot_budget <= 0 or not seq.tenant:
            return False
        held = sum(
            1 for s in self._slots
            if s is not None and s.tenant == seq.tenant
        )
        if held < self._tenant_slot_budget:
            return False
        return self._tenant_contended(seq.tenant)

    def _budget_denies_grow(self, seq: "_Seq", n_tokens: int) -> bool:
        """Decode-growth KV budget: an over-share tenant's sequence is
        recompute-preempted (it pays with its own latency) instead of
        squeezing other tenants out of the pool."""
        if self._tenant_kv_budget <= 0 or not seq.tenant or seq.alloc is None:
            return False
        extra = self.allocator.blocks_needed(
            min(n_tokens, self.config.max_model_len)
        ) - len(seq.alloc.block_ids)
        if extra <= 0:
            return False
        held = self.allocator.tenant_blocks.get(seq.tenant, 0)
        if held + extra <= self._tenant_kv_budget:
            return False
        return self._tenant_contended(seq.tenant)

    def _preempt_victim_for(self, seq: "_Seq") -> "_Seq":
        """Class-aware preemption: when ``seq`` needs blocks the pool
        can't yield, prefer preempting an active sequence of a LOWER
        class (or of a tenant over its KV budget) — lowest level first,
        most blocks held within a level. Falls back to ``seq`` itself
        (the pre-QoS behavior) when no better victim exists. The
        reclaimable tier is already class-ordered in the allocator; this
        extends the same order to hard-held blocks."""
        if self._fair is None:
            return seq
        best = None
        for s in self._slots:
            if s is None or s is seq or s.tenant == seq.tenant or s.alloc is None:
                continue
            over = (
                self._tenant_kv_budget > 0
                and self.allocator.tenant_blocks.get(s.tenant, 0)
                > self._tenant_kv_budget
            )
            if s.level < seq.level or over:
                key = (s.level, -len(s.alloc.block_ids))
                if best is None or key < best[0]:
                    best = (key, s)
        return best[1] if best is not None else seq

    def _admit_inner(self, deferred: List["_Seq"]) -> None:
        while True:
            with self._cond:
                if not self._pending:
                    return
                free = [i for i, s in enumerate(self._slots) if s is None]
                if not free:
                    return
                seq = self._pop_pending_locked()
            if seq.ctx.context.is_stopped:
                if seq.alloc is not None:
                    self.allocator.free_sequence(seq.alloc)
                    seq.alloc = None
                seq.emit(Annotated.from_data(LLMEngineOutput.final(FinishReason.CANCELLED).to_dict()))
                seq.emit(_FINISHED)
                continue
            if seq.alloc is None and getattr(seq.request, "migrate", None):
                # re-homed migrated stream: adopt the staged allocation
                # (cached_tokens = N-1 ⇒ the prefill below computes exactly
                # one fresh position). Miss/mismatch falls through to the
                # ordinary resume recompute.
                self._adopt_staged(seq)
            if seq.alloc is not None and seq.generated:
                # remotely-prefilled sequence re-entering for a decode slot:
                # KV + first token already landed, just start decoding
                seq.slot = free[0]
                self._slots[seq.slot] = seq
                if seq.admit_t is None:
                    seq.admit_t = time.perf_counter()
                continue
            if seq.alloc is not None:
                # remote prefill failed/timed out: run the prefill locally on
                # the allocation we already hold
                seq.slot = free[0]
                self._slots[seq.slot] = seq
                if seq.admit_t is None:
                    seq.admit_t = time.perf_counter()
                seq.prefill_pos = min(seq.alloc.cached_tokens, len(seq.prompt) - 1)
                continue
            if seq.wait_hash is not None:
                if self.allocator.inflight_pending(seq.wait_hash):
                    # still parked on another lane's in-flight prefix: skip
                    # the full re-probe (an O(prompt) hash walk per loop
                    # iteration that would also inflate probe metrics)
                    deferred.append(seq)
                    continue
                seq.wait_hash = None
            if self._fair is not None and (
                self._kv_budget_defers(seq) or self._slot_budget_defers(seq)
            ):
                # tenant over its KV or slot share while others are active:
                # park this request (its own latency pays) — the scheduler
                # keeps admitting other tenants past it
                deferred.append(seq)
                continue
            alloc = self._alloc_seq_timed(seq)
            if isinstance(alloc, InflightPrefix):
                # another lane is prefilling this prompt's prefix right now:
                # park until it seals (then these become ordinary prefix
                # hits) instead of computing the same blocks twice. Other
                # pending requests keep admitting past this one.
                seq.joined_inflight = True
                seq.wait_hash = alloc.seq_hash
                deferred.append(seq)
                continue
            if alloc is None and (self._inflight is not None or self._zombie_allocs):
                # blocks may be parked behind the in-flight speculative chunk
                self._drain_inflight()
                alloc = self._alloc_seq_timed(seq)
                if isinstance(alloc, InflightPrefix):
                    seq.joined_inflight = True
                    seq.wait_hash = alloc.seq_hash
                    deferred.append(seq)
                    continue
            if alloc is None and self._fair is not None:
                # class-aware preemption: reclaim from a lower-class (or
                # over-budget) tenant's active sequence before giving up.
                # The in-flight chunk is drained first so freed pages can't
                # still receive its speculative writes.
                victim = self._preempt_victim_for(seq)
                if victim is not seq:
                    self._drain_inflight()
                    self._preempt(victim)
                    alloc = self._alloc_seq_timed(seq)
                    if isinstance(alloc, InflightPrefix):
                        seq.joined_inflight = True
                        seq.wait_hash = alloc.seq_hash
                        deferred.append(seq)
                        continue
            if alloc is None:
                if not any(self._slots) and not self._awaiting:
                    # nothing running (or awaiting remote prefill) will ever
                    # free blocks: impossible request
                    seq.emit(Annotated.from_error(
                        f"prompt needs {self.allocator.blocks_needed(len(seq.prompt))} "
                        f"KV blocks; pool has {self.num_blocks}"
                    ))
                    seq.emit(_FINISHED)
                    continue
                with self._cond:
                    self._pending.appendleft(seq)  # retry when blocks free up
                return
            seq.alloc = alloc
            if seq.resumed:
                # the chaos-gate observable (docs/resilience.md §Live
                # migration): positions of another worker's dead stream this
                # admission recomputes. The last position is excluded — it
                # was never computed anywhere (the source sampled its token
                # but hadn't fed it). A migrate-adopted admission never
                # reaches this line (its staged alloc covers everything).
                self.resume_recompute_tokens += max(
                    len(seq.prompt) - alloc.cached_tokens - 1, 0
                )
            if seq.joined_inflight:
                # telemetry: tokens this request got for free by waiting for
                # a concurrent identical prefix instead of recomputing it
                self.allocator.shared_prefill_tokens += alloc.cached_tokens
                seq.joined_inflight = False
            if alloc.host_hits:
                # must land before ANY path uses the allocation: both local
                # prefill and remote-prefill submission treat cached_tokens
                # (which counts host hits) as valid device KV
                self._inject_host_hits(alloc)
            if seq.emitted == 0:  # don't re-count preempted re-admissions
                self.total_requests += 1
                self.total_prompt_tokens += len(seq.prompt)

            # conditional disaggregation: long-enough prefills (minus whatever
            # the prefix cache already covers) go to a remote prefill worker
            policy = self._remote_policy
            uncached = len(seq.prompt) - alloc.cached_tokens
            if (
                policy is not None
                and not seq.remote
                and policy.should_remote(uncached)
            ):
                seq.remote = True
                seq.remote_deadline = time.perf_counter() + self.config.remote_prefill_timeout
                self._awaiting[seq.ctx.id] = seq
                first_suffix_block = alloc.cached_tokens // self.config.kv_block_size
                # trace context rides the prefill request so the remote
                # worker's spans join THIS request's trace (one trace across
                # disaggregated prefill/decode)
                tp = (
                    tracing.format_traceparent(seq.ctx.context.trace)
                    if tracing.enabled() else None
                )
                policy.submit(
                    request_id=seq.ctx.id,
                    token_ids=seq.prompt,
                    block_ids=list(alloc.block_ids[first_suffix_block:]),
                    cached_tokens=alloc.cached_tokens,
                    sampling={
                        "temperature": seq.temperature, "top_k": seq.top_k,
                        "top_p": seq.top_p, "seed": seq.seed,
                    },
                    traceparent=tp or "",
                    # pages backing the cached prefix: the prefill worker
                    # reads these (transfer-plane read_blocks) instead of
                    # recomputing the shared history
                    prefix_block_ids=list(alloc.block_ids[:first_suffix_block]),
                )
                continue  # holds no slot while prefill runs remotely

            seq.slot = free[0]
            self._slots[seq.slot] = seq
            if seq.admit_t is None:
                seq.admit_t = time.perf_counter()
            # the last prompt token is never cached (allocator guarantees it),
            # so every admitted sequence computes at least one position
            seq.prefill_pos = seq.alloc.cached_tokens

    # -- performance attribution (runtime/profiling.py) ----------------------

    def _alloc_seq_timed(self, seq: "_Seq"):
        """allocate_sequence with the allocator time accrued into the next
        dispatch record (profiling armed) — the bare call otherwise."""
        tl = self._timeline
        if tl is None:
            return self.allocator.allocate_sequence(
                seq.prompt, tenant=seq.tenant, level=seq.level
            )
        t = time.perf_counter()
        alloc = self.allocator.allocate_sequence(
            seq.prompt, tenant=seq.tenant, level=seq.level
        )
        self._prof_alloc_us += (time.perf_counter() - t) * 1e6
        return alloc

    def _seal_timed(self, alloc, toks) -> None:
        """note_tokens_computed (block seal + integrity checksum) with the
        time accrued to the allocator share of the dispatch record."""
        tl = self._timeline
        if tl is None:
            self.allocator.note_tokens_computed(alloc, toks)
            return
        t = time.perf_counter()
        self.allocator.note_tokens_computed(alloc, toks)
        self._prof_alloc_us += (time.perf_counter() - t) * 1e6

    def _note_dispatch(
        self, tl, phase: str, t_step: float, t_disp: float, t_fetch: float,
        t_end: float, batch: int, tokens: int,
    ) -> None:
        """One sampled dispatch into the timeline: host build / device /
        host emit split, the accrued allocator share, queue depths, and the
        PR5 request/trace ids riding the batch."""
        alloc_us, self._prof_alloc_us = self._prof_alloc_us, 0.0
        reqs: List[str] = []
        traces: List[str] = []
        for s in self._slots:
            if s is None or len(reqs) >= 8:
                continue
            reqs.append(str(s.ctx.id))
            tr = getattr(s.ctx.context, "trace", None)
            tid = getattr(tr, "trace_id", None)
            if tid:
                traces.append(str(tid))
        # epoch-align the perf_counter anchors so captures from different
        # workers merge onto one Perfetto timeline
        now_wall = time.time()  # dynlint: allow-wall-clock(cross-process trace alignment)
        now_perf = time.perf_counter()
        tl.note_dispatch(
            phase,
            ts=now_wall - (now_perf - t_step),
            step=self._step_counter,
            batch=batch,
            tokens=tokens,
            host_us=(t_disp - t_step) * 1e6,
            device_us=(t_fetch - t_disp) * 1e6,
            post_us=(t_end - t_fetch) * 1e6,
            alloc_us=alloc_us,
            queue=len(self._pending) + len(self._awaiting),
            reqs=reqs,
            traces=traces,
        )

    def _dispatch_step(self) -> None:
        active = [s for s in self._slots if s is not None]
        if not active:
            self._prefill_debt = 0.0  # contention episode over
            self._drain_inflight()
            return
        prefilling = any(s.prefill_pos is not None for s in active)
        if not prefilling and self._prefill_debt:
            # debt is only meaningful WITHIN one prefill/decode contention
            # episode: once no lane is prefilling, drop it — a prompt
            # arriving minutes later must not inherit a finished prompt's
            # debt as extra TTFT
            self._prefill_debt = 0.0
        if (
            prefilling
            and self._prefill_budget > 0
            and any(s.prefill_pos is None for s in active)
        ):
            # duty-cycled interleave (DYN_TPU_PREFILL_BUDGET, docs/qos.md):
            # a chunk dispatch costs full [S, C] compute no matter how few
            # real tokens it feeds, and it advances decode lanes by ONE
            # token where a pipelined decode dispatch advances them
            # decode_steps — so isolation comes from dispatch FREQUENCY,
            # not from shrinking a dispatch. Every dispatch earns `budget`
            # tokens of prefill credit; a chunk dispatch spends what it
            # consumed. While in debt, prefill lanes sit the dispatch out
            # and decode runs at full pipelined speed: on average at most
            # `budget` prefill tokens ride each dispatch, so a long prompt
            # stretches its OWN TTFT instead of every decode lane's ITL.
            # Idle decode ⇒ this path never taken: prefill at full speed.
            self._prefill_debt = max(
                self._prefill_debt - self._prefill_budget, 0.0
            )
            if self._prefill_debt > 0:
                self._decode_step()
                return
            self._drain_inflight()
            self._chunk_step(paced=True)
            return
        if prefilling:
            # chunk prefill needs each decode lane's true last token host-side
            self._drain_inflight()
            self._chunk_step()
        elif (
            self._spec_k > 0
            and self._dispatch_hook is None
            and not self._multihost
            and any(
                s.drafter is not None and s.drafter.would_draft()
                for s in active
            )
        ):
            # all lanes decoding and at least one drafter's index holds a
            # usable match (would_draft: dormancy + a pre-drain probe of
            # the suffix index — a verify dispatch costs a pipeline drain,
            # so lanes that can't possibly propose must not pay it): try a
            # verify dispatch (it still falls back to the plain pipelined
            # decode step when, after draining, no lane actually drafts).
            # Multihost followers only replay chunk/decode opcodes, so the
            # leader keeps speculation off on a process-spanning mesh.
            self._verify_step()
        else:
            self._decode_step()

    def _chunk_step(self, paced: bool = False) -> None:
        """One [slots, prefill_chunk] dispatch: prefilling lanes consume up to
        a chunk of prompt; decode lanes advance one token. A whole admission
        wave prefills in ceil(longest_suffix / chunk) dispatches instead of
        one serial batch-1 dispatch per request (the round-1 18 s TTFT).

        ``paced`` (the prefill-budget duty cycle, _dispatch_step): decode
        lanes are live, so total prefill consumption is capped at ONE
        chunk, handed to the most-starved tenant's lanes first, and the
        consumed tokens are charged to the prefill debt that keeps the
        following dispatches pure-decode."""
        cfg = self.config
        S, C = cfg.max_slots, cfg.prefill_chunk
        tl = self._timeline
        t_step = (
            time.perf_counter()
            if tl is not None or self._straggler is not None else 0.0
        )
        for seq in [s for s in self._slots if s is not None]:
            if seq.slot is None:
                # an earlier lane's class-aware reclaim preempted this one
                # mid-pass: it left the slots (alloc freed) but is still in
                # the snapshot — touching it would grow a None alloc
                continue
            if seq.ctx.context.is_stopped:
                self._finish(seq, FinishReason.CANCELLED)
            elif seq.prefill_pos is None:
                # decode lane writes KV at position total_len-1
                need = min(seq.total_len, cfg.max_model_len)
                if self._fair is not None and self._budget_denies_grow(seq, need):
                    self._preempt(seq)  # over-share tenant pays, not others
                elif not self.allocator.grow(seq.alloc, need):
                    victim = self._preempt_victim_for(seq)
                    self._preempt(victim)
                    if victim is not seq and not self.allocator.grow(
                        seq.alloc, need
                    ):
                        self._preempt(seq)
        if tl is not None:
            # the loop above is grow/evict work: the allocator share of
            # this dispatch's host overhead
            self._prof_alloc_us += (time.perf_counter() - t_step) * 1e6
        if not any(self._slots):
            return

        # paced dispatch (prefill-budget duty cycle): one chunk's worth of
        # prefill total this dispatch, most-starved tenant's lanes first —
        # fairness decides WHOSE long prompt advances while decode lanes
        # ride along. allow=None is the unpaced fast path (identical to
        # pre-budget behavior).
        allow: Optional[Dict[int, int]] = None
        if paced:
            pre = [
                i for i in range(S)
                if self._slots[i] is not None
                and self._slots[i].prefill_pos is not None
            ]
            if pre:
                if self._fair is not None and len(pre) > 1:
                    pre.sort(key=lambda i: self._fair.vt(self._slots[i].tenant))
                rem = [
                    len(self._slots[i].prompt) - self._slots[i].prefill_pos
                    for i in pre
                ]
                allow = dict(zip(
                    pre, qos_mod.split_prefill_budget(rem, C, C),
                ))

        tokens = np.zeros((S, C), np.int32)
        positions = np.full((S, C), -1, np.int32)
        sample_at = np.full((S,), -1, np.int32)
        consumed: List[Optional[List[int]]] = [None] * S
        n_prefill = 0
        has_decode = False
        for i in range(S):
            seq = self._slots[i]
            self._tables[i, :] = 0
            self._temp[i] = 0.0
            self._topk[i] = 0
            self._topp[i] = 1.0
            self._seeds[i] = 0
            self._freqp[i] = 0.0
            self._presp[i] = 0.0
            if seq is None:
                continue
            self._tables[i, : len(seq.alloc.block_ids)] = seq.alloc.block_ids
            self._temp[i] = seq.temperature
            self._topk[i] = seq.top_k
            self._topp[i] = seq.top_p
            self._seeds[i] = seq.seed & 0x7FFFFFFF
            self._freqp[i] = seq.freq_pen
            self._presp[i] = seq.pres_pen
            if seq.prefill_pos is not None:
                n = min(C, len(seq.prompt) - seq.prefill_pos)
                if allow is not None:
                    n = min(n, allow.get(i, 0))
                if n <= 0:
                    continue  # budgeted out of this step; advances next one
                chunk_toks = seq.prompt[seq.prefill_pos : seq.prefill_pos + n]
                tokens[i, :n] = chunk_toks
                positions[i, :n] = np.arange(seq.prefill_pos, seq.prefill_pos + n)
                if seq.prefill_pos + n == len(seq.prompt):
                    sample_at[i] = n - 1
                consumed[i] = chunk_toks
                n_prefill += n
            else:
                fed = seq.generated[-1] if seq.generated else seq.prompt[-1]
                tokens[i, 0] = fed
                positions[i, 0] = seq.total_len - 1
                sample_at[i] = 0
                consumed[i] = [fed]
                has_decode = True
        if has_decode and n_prefill > self.prefill_interleave_max:
            # interleaving bound: the most prefill work any dispatch ever
            # put in front of a live decode lane (the ITL-isolation tests
            # assert it stays ≤ one chunk under pacing, vs the full prompt
            # on the unbudgeted control leg)
            self.prefill_interleave_max = n_prefill
        if paced and has_decode:
            self._prefill_debt += n_prefill

        self._step_counter += 1
        want_lp = any(
            s is not None and s.logprobs is not None for s in self._slots
        )
        want_pen = any(s is not None and s.penalized for s in self._slots)
        want_sample = any(
            s is not None and s.temperature > 0.0 for s in self._slots
        )
        # a fresh admission wave's first chunk (every lane starting at
        # position 0) attends nothing in the pool: compile out the history
        # gather + partial — this is THE TTFT-critical dispatch
        want_history = any(
            s is not None and (s.prefill_pos is None or s.prefill_pos > 0)
            for s in self._slots
        )
        if want_pen:
            self._sync_counts(list(self._slots))
        counts_in = self._counts if want_pen else self._dummy_counts
        ipack_np = np.stack([self._seeds, self._topk])
        fpack_np = np.stack([self._temp, self._topp, self._freqp, self._presp])
        if self._dispatch_hook is not None:
            # multihost leader: followers run the SAME dispatch in lockstep
            self._dispatch_hook(
                "chunk",
                dict(lp=want_lp, pen=want_pen, sample=want_sample,
                     history=want_history, step=self._step_counter),
                dict(tokens=tokens, positions=positions, tables=self._tables,
                     sample_at=sample_at, ipack=ipack_np, fpack=fpack_np),
            )
        args = (
            self.params, self.cache, counts_in, self._put(tokens),
            self._put(positions),
            self._m_tables.get(self._tables), self._put(sample_at),
            self._put(np.int32(self._step_counter)),
            self._m_ipack.get(ipack_np),
            self._m_fpack.get(fpack_np),
        ) + self._wd_args()
        self._slow_fault()
        prof = tl is not None and tl.should_sample()
        t_disp = time.perf_counter() if prof else 0.0
        # copy_to_host_async right after dispatch: the host-fetch path has a
        # ~100 ms fixed latency on a tunneled chip when started cold at get
        # time; started here it overlaps the chunk's own compute (measured
        # 120 ms -> <1 ms residual get)
        if want_lp:
            sampled, lp, tids, tlps, self.cache, counts_out = self._chunk(
                True, want_pen, want_sample, want_history
            )(*args)
            for arr in (sampled, lp, tids, tlps):
                arr.copy_to_host_async()
            # dynlint: allow-host-sync(leader sync: one fetch per chunk
            # dispatch, overlapped by copy_to_host_async above)
            sampled_np, lp_np, tids_np, tlps_np = jax.device_get(
                (sampled, lp, tids, tlps)
            )
        else:
            sampled, self.cache, counts_out = self._chunk(
                False, want_pen, want_sample, want_history
            )(*args)
            sampled.copy_to_host_async()
            # dynlint: allow-host-sync(leader sync: one fetch per chunk dispatch)
            sampled_np = jax.device_get(sampled)
            lp_np = tids_np = tlps_np = None
        t_fetch = time.perf_counter() if prof else 0.0
        if want_pen:
            self._counts = counts_out
        else:
            self._dummy_counts = counts_out
            self._release_counts()

        for i in range(S):
            seq = self._slots[i]
            if seq is None or consumed[i] is None:
                continue
            self._seal_timed(seq.alloc, consumed[i])
            lpinfo = (
                (float(lp_np[i]), tids_np[i], tlps_np[i])
                if lp_np is not None
                else None
            )
            tok = int(sampled_np[i])
            if seq.prefill_pos is not None:
                if self._fair is not None and seq.tenant:
                    # prefill progress bills the tenant's virtual clock
                    # (decode tokens bill in _emit_token/_emit_token_run)
                    self._fair.charge(seq.tenant, len(consumed[i]), seq.weight)
                seq.prefill_pos += len(consumed[i])
                if seq.prefill_pos >= len(seq.prompt):
                    if self._watchdog and tok < 0:
                        # watchdog sentinel on the lane's FIRST token: no
                        # token has reached the client yet, but the stream
                        # still ends typed + in-band so the caller re-homes
                        self._watchdog_trip(seq)
                        continue
                    seq.prefill_pos = None
                    seq.first_token_t = time.perf_counter()
                    self._emit_token(seq, tok, lpinfo=lpinfo)
            else:
                if self._watchdog and tok < 0:
                    self._watchdog_trip(seq)
                    continue
                self._emit_token(seq, tok, lpinfo=lpinfo)
        if prof:
            self._note_dispatch(
                tl, "chunk", t_step, t_disp, t_fetch, time.perf_counter(),
                batch=sum(1 for c in consumed if c is not None),
                tokens=sum(len(c) for c in consumed if c),
            )
        elif tl is not None:
            # unsampled dispatch: drop the accrued allocator share so it
            # can't pile up across the sampling stride and misattribute
            self._prof_alloc_us = 0.0
        if self._straggler is not None:
            self._straggler_tick(
                "chunk", t_step, sum(len(c) for c in consumed if c)
            )

    def _decode_step(self) -> None:
        """Pipelined decode: dispatch chunk N+1 off the previous dispatch's
        device-resident carry, THEN fetch + process chunk N. The host↔device
        round trip (which on a tunneled chip rivals the chunk's compute time)
        overlaps the next chunk's execution. Blocks owned by sequences that
        finish mid-pipeline receive up to one chunk of speculative garbage
        writes, so their allocations are parked in ``_zombie_allocs`` and
        freed only once the in-flight chunk has been fetched."""
        cfg = self.config
        S, k = cfg.max_slots, cfg.decode_steps
        tl = self._timeline
        t_step = (
            time.perf_counter()
            if tl is not None or self._straggler is not None else 0.0
        )

        stopped = [s for s in self._slots if s is not None and s.ctx.context.is_stopped]
        if stopped:
            self._drain_inflight()
            for seq in stopped:
                if seq.slot is not None:
                    self._finish(seq, FinishReason.CANCELLED)

        # capacity: this chunk writes positions total_len-1 .. total_len-2+k,
        # and the next (speculative) chunk another k past that. Prefilling
        # lanes (paced duty cycle: they sit decode dispatches out) neither
        # grow nor dispatch here.
        t_grow = time.perf_counter() if tl is not None else 0.0
        while True:
            ok = True
            for seq in [s for s in self._slots if s is not None]:
                if seq.prefill_pos is not None:
                    continue
                need = min(seq.total_len - 1 + 2 * k, cfg.max_model_len)
                denied = (
                    self._fair is not None
                    and self._budget_denies_grow(seq, need)
                )
                if denied or not self.allocator.grow(seq.alloc, need):
                    if self._inflight is not None or self._zombie_allocs:
                        self._drain_inflight()  # releases zombie blocks
                    elif denied:
                        # tenant over its KV share under contention: its
                        # own sequence recompute-preempts (isolation —
                        # the overrun pays, not the neighbors)
                        self._preempt(seq)
                    else:
                        # class-aware: reclaim from a lower-class or
                        # over-budget tenant first; falls back to seq
                        self._preempt(self._preempt_victim_for(seq))
                    ok = False
                    break
            if ok:
                break
        if tl is not None:
            # grow/evict/preempt work: the allocator share of this
            # dispatch's host overhead
            self._prof_alloc_us += (time.perf_counter() - t_grow) * 1e6
        active = [
            s for s in self._slots
            if s is not None and s.prefill_pos is None
        ]
        if not active:
            return

        lanes = list(self._slots)
        if self._inflight is not None and any(
            a is not b for a, b in zip(self._inflight.lanes, lanes)
        ):
            # lane set changed since the in-flight dispatch: its carry no
            # longer matches; fall back to host-built inputs
            self._drain_inflight()
            lanes = list(self._slots)
            if not any(lanes):
                return

        # Don't dispatch a chunk nothing needs: if every active lane provably
        # reaches a length stop within the already-in-flight chunk, a
        # speculative dispatch would compute decode_steps of garbage that the
        # NEXT admission wave then queues behind (at large decode_steps that
        # stalls a whole wave's TTFT by a full chunk).
        def lane_needs_more(seq: "_Seq") -> bool:
            ahead = k if (
                self._inflight is not None
                and seq.slot is not None
                and self._inflight.lanes[seq.slot] is seq
            ) else 0
            if seq.emitted + ahead >= seq.max_tokens:
                return False
            if seq.total_len + ahead >= self.config.max_model_len:
                return False
            return True

        if not any(
            lane_needs_more(s) for s in lanes
            if s is not None and s.prefill_pos is None
        ):
            self._drain_inflight()
            return

        for i in range(S):
            seq = self._slots[i]
            self._tables[i, :] = 0
            if seq is None or seq.prefill_pos is not None:
                # empty lane — or a prefilling lane sitting this paced
                # decode dispatch out (position -1 keeps it inert in-jit)
                self._positions[i] = -1
                self._last_tokens[i] = 0
                self._temp[i] = 0.0
                self._topk[i] = 0
                self._topp[i] = 1.0
                self._seeds[i] = 0
                self._freqp[i] = 0.0
                self._presp[i] = 0.0
                continue
            self._positions[i] = seq.total_len - 1
            self._last_tokens[i] = seq.generated[-1] if seq.generated else seq.prompt[-1]
            self._tables[i, : len(seq.alloc.block_ids)] = seq.alloc.block_ids
            self._temp[i] = seq.temperature
            self._topk[i] = seq.top_k
            self._topp[i] = seq.top_p
            self._seeds[i] = seq.seed & 0x7FFFFFFF
            self._freqp[i] = seq.freq_pen
            self._presp[i] = seq.pres_pen

        use_carry = self._inflight is not None
        if use_carry:
            toks_in, pos_in = self._inflight.tokens, self._inflight.positions
        else:
            toks_in = self._put(self._last_tokens)
            pos_in = self._put(self._positions)

        self._step_counter += 1
        live = [
            s if (s is not None and s.prefill_pos is None) else None
            for s in lanes
        ]
        want_lp = any(s is not None and s.logprobs is not None for s in live)
        want_pen = any(s is not None and s.penalized for s in live)
        want_sample = any(s is not None and s.temperature > 0.0 for s in live)
        if want_pen:
            self._sync_counts(lanes)
        counts_in = self._counts if want_pen else self._dummy_counts
        ipack_np = np.stack([self._seeds, self._topk])
        fpack_np = np.stack([self._temp, self._topp, self._freqp, self._presp])
        if self._dispatch_hook is not None:
            self._dispatch_hook(
                "decode",
                dict(lp=want_lp, pen=want_pen, sample=want_sample,
                     use_carry=use_carry, step=self._step_counter),
                dict(tokens=self._last_tokens, positions=self._positions,
                     tables=self._tables, ipack=ipack_np, fpack=fpack_np),
            )
        args = (
            self.params_decode, self.cache, counts_in, toks_in, pos_in,
            self._m_tables.get(self._tables),
            self._put(np.int32(self._step_counter)),
            self._m_ipack.get(ipack_np),
            self._m_fpack.get(fpack_np),
        ) + self._wd_args()
        self._slow_fault()
        prof = tl is not None and tl.should_sample()
        t_disp = time.perf_counter() if prof else 0.0
        if want_lp:
            out, lps, tids, tlps, toks2, pos2, self.cache, counts_out = (
                self._decode(True, want_pen, want_sample)(*args)
            )
        else:
            out, toks2, pos2, self.cache, counts_out = self._decode(
                False, want_pen, want_sample
            )(*args)
            lps = tids = tlps = None
        if prof:
            # the profiling contract: block-until-ready device time for the
            # SAMPLED dispatch (serializes this one dispatch of the
            # pipeline; sample_every bounds the tax)
            # dynlint: allow-host-sync(sampled profiling dispatch: device-time measurement)
            jax.block_until_ready(out)
            t_fetch = time.perf_counter()
        if want_pen:
            self._counts = counts_out
        else:
            self._dummy_counts = counts_out
            self._release_counts()
        prev, self._inflight = (
            self._inflight, _Inflight(out, lps, tids, tlps, toks2, pos2, lanes)
        )
        # start the host copies now: by the time this chunk is processed (one
        # pipelined dispatch later) the fetch has ridden the previous chunk's
        # compute window and the blocking get is ~free (vs ~100 ms cold)
        for arr in (out, lps, tids, tlps):
            if arr is not None:
                arr.copy_to_host_async()
        if prev is not None:
            self._process_chunk(prev, defer_free=True)
        if prof:
            self._note_dispatch(
                tl, "decode", t_step, t_disp, t_fetch, time.perf_counter(),
                batch=len(active), tokens=len(active) * k,
            )
        elif tl is not None:
            self._prof_alloc_us = 0.0
        if self._straggler is not None:
            self._straggler_tick("decode", t_step, len(active) * k)

    def _emit_token_run(
        self,
        seq: "_Seq",
        cand: List[int],
        lp_rows,  # None, or (lps_row [k], tids_row [k, n_top], tlps_row)
        *,
        defer_free: bool = False,
    ) -> int:
        """Emit one multi-token run for a lane — the shared tail of the
        pipelined chunk and the speculative verify dispatch. Cuts the
        candidate run at max_tokens / max_model_len / first EOS, registers
        fed-token KV, assembles logprobs, emits ONE item (per-token emission
        costs a dict build + a call_soon_threadsafe wakeup each — at 32
        lanes × 64-step chunks that Python overhead rivals the decode step's
        device time), and finishes the lane on a terminal cut. Returns the
        number of tokens actually emitted."""
        if self._watchdog and any(t < 0 for t in cand):
            # output watchdog sentinel: this dispatch produced non-finite /
            # exploding logits for the lane. NOTHING from the run is
            # emitted or sealed — the whole run is suspect — and the lane
            # ends typed + in-band (resume directive) so the client
            # re-admits on a sibling (docs/resilience.md §Silent corruption)
            self._watchdog_trip(seq, defer_free=defer_free)
            return 0
        cfg = self.config
        n_take = min(
            len(cand),
            seq.max_tokens - seq.emitted,
            cfg.max_model_len - seq.total_len,
        )
        finish: Optional[FinishReason] = None
        if n_take < len(cand):
            finish = FinishReason.LENGTH
        toks = cand[:n_take]
        if seq.eos_ids and not seq.ignore_eos:
            for j, t in enumerate(toks):
                if t in seq.eos_ids:
                    toks = toks[: j + 1]
                    finish = FinishReason.EOS
                    break
        if not toks:
            if finish is not None:
                self._finish(seq, finish, defer_free=defer_free)
            return 0
        if finish is None and seq.emitted + len(toks) >= seq.max_tokens:
            finish = FinishReason.LENGTH
        elif finish is None and seq.total_len + len(toks) >= cfg.max_model_len:
            finish = FinishReason.LENGTH
        # fed tokens whose KV is valid AND part of the sequence: the carried
        # last token plus every emitted token bar the final one (in the
        # verify dispatch, matched drafts ARE the emitted prefix)
        fed0 = seq.generated[-1] if seq.generated else seq.prompt[-1]
        self._seal_timed(seq.alloc, [fed0] + toks[:-1])

        log_probs = top_logprobs = None
        if lp_rows is not None and seq.logprobs is not None:
            lps_row, tids_row, tlps_row = lp_rows
            n = len(toks)
            log_probs = [float(x) for x in lps_row[:n]]
            if seq.logprobs > 0:
                kk = min(seq.logprobs, tids_row.shape[1])
                top_logprobs = [
                    {int(tids_row[j, p]): float(tlps_row[j, p])
                     for p in range(kk)}
                    for j in range(n)
                ]
        seq.generated.extend(toks)
        seq.out_tokens.extend(toks)
        if seq.drafter is not None:
            seq.drafter.extend(toks)
        seq.emitted += len(toks)
        self.total_generated_tokens += len(toks)
        if self._fair is not None and seq.tenant:
            self._fair.charge(seq.tenant, len(toks), seq.weight)
        seq.emit(Annotated.from_data(
            LLMEngineOutput(
                token_ids=toks, log_probs=log_probs, top_logprobs=top_logprobs
            ).to_dict(),
            id=seq.ctx.id,
        ))
        if finish is not None:
            self._finish(seq, finish, defer_free=defer_free)
        return len(toks)

    def _process_chunk(self, chunk: _Inflight, defer_free: bool) -> None:
        if self._perf is not None:
            # gap between consecutive processed chunks ≈ chunk wall time in
            # pipelined decode; tokens counted below feed the tps EMA
            tokens_before = self.total_generated_tokens
            self._perf.note_slots(
                sum(1 for s in chunk.lanes if s is not None),
                self.config.max_slots,
            )
        if chunk.lps is not None:
            # dynlint: allow-host-sync(leader sync: pipelined fetch — the copy
            # rode the NEXT chunk's compute window, ~free by the time we get)
            out, lps, tids, tlps = jax.device_get(
                (chunk.out, chunk.lps, chunk.top_ids, chunk.top_lps)
            )
        else:
            # dynlint: allow-host-sync(leader sync: pipelined fetch, see above)
            out = jax.device_get(chunk.out)
            lps = tids = tlps = None
        out = np.asarray(out)  # [S, k_steps]
        for i, seq in enumerate(chunk.lanes):
            if seq is None or seq.slot != i:
                continue  # empty lane, or finished in an earlier chunk
            if seq.prefill_pos is not None:
                # prefilling lane that sat a paced decode dispatch out
                # (position -1 in-jit): its row is garbage, not tokens
                continue
            self._emit_token_run(
                seq,
                [int(t) for t in out[i]],
                (lps[i], tids[i], tlps[i]) if lps is not None else None,
                defer_free=defer_free,
            )
        if self._perf is not None:
            self._perf.note_decode(
                self.total_generated_tokens - tokens_before,
                self.config.decode_steps,
            )

    def _verify_step(self) -> None:
        """One speculative-verify dispatch (self-draft, engine_jax/drafter.py).

        Probes every decode lane's n-gram drafter, feeds ``[last_token,
        draft_0..draft_{k-1}]`` through the jit verify variant (one weight
        stream for all K1 positions), and accepts the longest drafted prefix
        matching the in-jit sampled targets plus the first non-matching
        target as the bonus token. Greedy output is bitwise identical to the
        sequential decode path; sampled output follows the exact
        autoregressive distribution (speculative_targets docstring).

        Not pipelined: the next dispatch's fed tokens depend on this one's
        acceptance, so the chunk is fetched synchronously — the amortized
        weight stream is what pays for the lost overlap. When no lane
        drafts (cold drafters, dormant after sustained rejection), control
        falls through to the plain pipelined decode step, so adversarial
        workloads keep the non-speculative fast path."""
        cfg = self.config
        S = cfg.max_slots
        tl = self._timeline
        t_step = (
            time.perf_counter()
            if tl is not None or self._straggler is not None else 0.0
        )
        # host needs every lane's true last token and the drafters need the
        # emitted suffix up to date before proposing
        self._drain_inflight()
        for seq in [
            s for s in self._slots
            if s is not None and s.ctx.context.is_stopped
        ]:
            self._finish(seq, FinishReason.CANCELLED)
        if not any(self._slots):
            return

        drafts: List[Optional[List[int]]] = [None] * S
        n_drafted = 0
        for i, seq in enumerate(self._slots):
            if seq is None or seq.drafter is None:
                continue
            # cap: fed positions must stay under max_model_len, and drafts
            # past the request's remaining token budget are dead weight
            cap = min(
                self._spec_k,
                cfg.max_model_len - seq.total_len,
                seq.max_tokens - seq.emitted,
            )
            if cap <= 0:
                continue
            d = seq.drafter.draft()
            if d:
                drafts[i] = d[:cap]
                n_drafted += len(drafts[i])
        if n_drafted == 0:
            self._decode_step()
            return

        # capacity for the drafted positions (non-pipelined: preemption here
        # has no zombie-chunk complication)
        for i, seq in enumerate(self._slots):
            if seq is None:
                continue
            need = min(seq.total_len + len(drafts[i] or []), cfg.max_model_len)
            if not self.allocator.grow(seq.alloc, need):
                drafts[i] = None
                self._preempt(seq)
        if not any(self._slots):
            return
        if not any(
            drafts[i] for i in range(S) if self._slots[i] is not None
        ):
            self._decode_step()
            return

        k1 = self._spec_k + 1
        tokens = np.zeros((S, k1), np.int32)
        positions = np.full((S, k1), -1, np.int32)
        for i in range(S):
            seq = self._slots[i]
            self._tables[i, :] = 0
            self._temp[i] = 0.0
            self._topk[i] = 0
            self._topp[i] = 1.0
            self._seeds[i] = 0
            self._freqp[i] = 0.0
            self._presp[i] = 0.0
            if seq is None:
                continue
            fed = [seq.generated[-1] if seq.generated else seq.prompt[-1]]
            fed += drafts[i] or []
            n = len(fed)
            tokens[i, :n] = fed
            positions[i, :n] = np.arange(seq.total_len - 1, seq.total_len - 1 + n)
            self._tables[i, : len(seq.alloc.block_ids)] = seq.alloc.block_ids
            self._temp[i] = seq.temperature
            self._topk[i] = seq.top_k
            self._topp[i] = seq.top_p
            self._seeds[i] = seq.seed & 0x7FFFFFFF
            self._freqp[i] = seq.freq_pen
            self._presp[i] = seq.pres_pen

        self._step_counter += 1
        lanes = list(self._slots)
        want_lp = any(s is not None and s.logprobs is not None for s in lanes)
        want_pen = any(s is not None and s.penalized for s in lanes)
        want_sample = any(s is not None and s.temperature > 0.0 for s in lanes)
        if want_pen:
            self._sync_counts(lanes)
        counts_in = self._counts if want_pen else self._dummy_counts
        ipack_np = np.stack([self._seeds, self._topk])
        fpack_np = np.stack([self._temp, self._topp, self._freqp, self._presp])
        args = (
            self.params_decode, self.cache, counts_in, self._put(tokens),
            self._put(positions), self._m_tables.get(self._tables),
            self._put(np.int32(self._step_counter)),
            self._m_ipack.get(ipack_np), self._m_fpack.get(fpack_np),
        ) + self._wd_args()
        self._slow_fault()
        prof = tl is not None and tl.should_sample()
        t_disp = time.perf_counter() if prof else 0.0
        if want_lp:
            tgt, lps, tids, tlps, self.cache, counts_out = self._verify(
                True, want_pen, want_sample
            )(*args)
            for arr in (tgt, lps, tids, tlps):
                arr.copy_to_host_async()
            # dynlint: allow-host-sync(leader sync: one fetch per verify
            # dispatch — acceptance decides the next dispatch's inputs, so
            # this path is deliberately not pipelined)
            tgt_np, lp_np, tids_np, tlps_np = jax.device_get(
                (tgt, lps, tids, tlps)
            )
        else:
            tgt, self.cache, counts_out = self._verify(
                False, want_pen, want_sample
            )(*args)
            tgt.copy_to_host_async()
            # dynlint: allow-host-sync(leader sync: one fetch per verify dispatch)
            tgt_np = np.asarray(jax.device_get(tgt))
            lp_np = tids_np = tlps_np = None
        t_fetch = time.perf_counter() if prof else 0.0
        if want_pen:
            self._counts = counts_out
        else:
            self._dummy_counts = counts_out
            self._release_counts()

        if self._perf is not None:
            tokens_before = self.total_generated_tokens
            self._perf.note_slots(
                sum(1 for s in self._slots if s is not None), S
            )
        drafted_total = accepted_total = 0
        fix_pairs: List[Tuple[int, int]] = []
        for i in range(S):
            seq = self._slots[i]
            if seq is None:
                continue
            d = drafts[i] or []
            row = tgt_np[i]
            a = 0
            while a < len(d) and int(row[a]) == d[a]:
                a += 1
            if d:
                seq.drafter.note_result(len(d), a)
                seq.spec_drafted += len(d)
                seq.spec_accepted += a
                self.spec_drafted_total += len(d)
                self.spec_accepted_total += a
                drafted_total += len(d)
                accepted_total += a
            penalized = seq.penalized
            # emitted run: matched drafts + the bonus target, then the same
            # cut rules as _process_chunk (shared _emit_token_run tail)
            n_emitted = self._emit_token_run(
                seq,
                [int(t) for t in row[: a + 1]],
                (lp_np[i], tids_np[i], tlps_np[i])
                if lp_np is not None else None,
            )
            if want_pen and penalized:
                # the scan added EVERY active position's target into this
                # lane's count row (sequential exactness up to the first
                # mismatch costs pollution past it); subtract the targets
                # that were NOT emitted — rejected positions plus any cut
                # by max_tokens / max_model_len / EOS
                for j in range(n_emitted, 1 + len(d)):
                    fix_pairs.append((i, int(row[j])))
        if fix_pairs and self._counts is not None:
            pb = 1
            while pb < len(fix_pairs):
                pb *= 2
            rows = np.full((pb,), S, np.int32)
            toks_np = np.zeros((pb,), np.int32)
            for j, (r, t) in enumerate(fix_pairs):
                rows[j] = r
                toks_np[j] = t
            self._counts = self._counts_fix_fn(pb)(
                self._counts, self._put(rows), self._put(toks_np)
            )
        if self._perf is not None:
            self._perf.note_decode(
                self.total_generated_tokens - tokens_before, 1
            )
            self._perf.note_spec(drafted_total, accepted_total)
        if prof:
            self._note_dispatch(
                tl, "verify", t_step, t_disp, t_fetch, time.perf_counter(),
                batch=sum(1 for s in self._slots if s is not None),
                tokens=accepted_total + sum(
                    1 for s in self._slots if s is not None
                ),
            )
        elif tl is not None:
            self._prof_alloc_us = 0.0
        if self._straggler is not None:
            self._straggler_tick(
                "verify", t_step,
                accepted_total + sum(
                    1 for s in self._slots if s is not None
                ),
            )

    def _drain_inflight(self) -> None:
        """Fetch + process any in-flight chunk, then release zombie blocks
        (no further speculative writes can touch them)."""
        if self._inflight is not None:
            chunk, self._inflight = self._inflight, None
            self._process_chunk(chunk, defer_free=False)
        for alloc in self._zombie_allocs:
            self.allocator.free_sequence(alloc)
        self._zombie_allocs.clear()

    def _emit_token(
        self, seq: _Seq, tok: int, defer_free: bool = False, lpinfo=None
    ) -> None:
        seq.generated.append(tok)
        seq.out_tokens.append(tok)
        if seq.drafter is not None:
            seq.drafter.extend((tok,))
        seq.emitted += 1
        self.total_generated_tokens += 1
        if self._fair is not None and seq.tenant:
            self._fair.charge(seq.tenant, 1, seq.weight)
        finish: Optional[FinishReason] = None
        if tok in seq.eos_ids and not seq.ignore_eos:
            finish = FinishReason.EOS
        elif seq.emitted >= seq.max_tokens:
            finish = FinishReason.LENGTH
        elif seq.total_len >= self.config.max_model_len:
            finish = FinishReason.LENGTH

        log_probs = top_logprobs = None
        if seq.logprobs is not None and lpinfo is not None:
            chosen_lp, top_ids, top_lps = lpinfo
            log_probs = [chosen_lp]
            if seq.logprobs > 0:
                k = min(seq.logprobs, len(top_ids))
                top_logprobs = [
                    {int(top_ids[p]): float(top_lps[p]) for p in range(k)}
                ]
        seq.emit(Annotated.from_data(
            LLMEngineOutput(
                token_ids=[tok], log_probs=log_probs, top_logprobs=top_logprobs
            ).to_dict(),
            id=seq.ctx.id,
        ))
        if finish is not None:
            self._finish(seq, finish, defer_free=defer_free)

    def _record_phase_spans(self, seq: _Seq, reason: FinishReason) -> None:
        """Retroactive phase spans from the timestamps the hot path already
        stamps (engine thread, once per request — dispatch loops stay
        allocation-free). queue_wait = enqueue → slot admission; prefill =
        admission → first token (remote prefills collapse queue_wait into
        prefill: the wait WAS the remote compute); decode = first token →
        finish, with the token count."""
        now = time.perf_counter()
        parent = seq.ctx.context.trace
        status = tracing.STATUS_OK
        if reason == FinishReason.CANCELLED:
            status = "cancelled"
        elif reason == FinishReason.ERROR:
            status = "error"
        attrs = {
            "request_id": seq.ctx.id,
            "prompt_tokens": len(seq.prompt),
            "output_tokens": seq.emitted,
            "remote_prefill": seq.remote,
            "finish_reason": str(getattr(reason, "value", reason)),
        }
        if seq.tenant:
            # per-tenant phase-latency attribution (docs/qos.md): every
            # phase span below parents here, so a tenant filter over the
            # flight recorder yields that tenant's queue/prefill/decode
            # breakdown
            attrs["tenant"] = seq.tenant
        if seq.resumed:
            # resumed re-admission: its "prefill" is a recovery recompute of
            # another worker's dead stream, not an admission wait — SLO
            # consumers exclude it from TTFT (docs/resilience.md)
            attrs["resumed"] = True
        if seq.migrated:
            # migrated re-home: the staged KV made the re-admission
            # recompute-free (docs/resilience.md §Live migration)
            attrs["migrated"] = True
        req_span = tracing.record_span(
            "engine.request", seq.enqueue_t, now, parent=parent,
            attributes=attrs,
            status=status,
        )
        parent = req_span or parent
        first = seq.first_token_t
        prefill_start = seq.enqueue_t
        if (
            seq.admit_t is not None
            and (first is None or seq.admit_t <= first)
        ):
            prefill_start = seq.admit_t
        tracing.record_span(
            "engine.queue_wait", seq.enqueue_t, prefill_start,
            parent=parent, phase="queue_wait",
        )
        if first is not None:
            tracing.record_span(
                "engine.prefill", prefill_start, first, parent=parent,
                phase="prefill",
                attributes={"remote": True} if seq.remote else None,
            )
            decode_attrs: Dict[str, Any] = {"tokens": seq.emitted}
            if seq.spec_drafted:
                # per-request speculation outcome on the decode span, plus a
                # dimensionless acceptance-rate observation (0..1) on the
                # spec_accept phase histogram — p50/p95 of per-request
                # acceptance through the same pipeline as the latencies
                decode_attrs["spec_drafted"] = seq.spec_drafted
                decode_attrs["spec_accepted"] = seq.spec_accepted
                tracing.observe_phase(
                    "spec_accept", seq.spec_accepted / seq.spec_drafted
                )
            tracing.record_span(
                "engine.decode", first, now, parent=parent, phase="decode",
                attributes=decode_attrs,
            )

    def _finish(self, seq: _Seq, reason: FinishReason, defer_free: bool = False) -> None:
        if tracing.enabled():
            self._record_phase_spans(seq, reason)
        if seq.slot is not None:
            self._slots[seq.slot] = None
            seq.slot = None
        if seq.alloc is not None:
            if seq.ctx.id in self._hold_ids:
                # prefill-worker mode: park the pages for extraction; the
                # caller frees via take_held_pages/release_held. Safe without
                # zombie-parking: held requests are prompt-only (finish in
                # the chunk step), so no speculative decode writes them.
                self._held_allocs[seq.ctx.id] = seq.alloc
                seq.alloc = None
            elif defer_free:
                # the in-flight speculative chunk may still write into these
                # blocks; park them until it has been fetched
                self._zombie_allocs.append(seq.alloc)
            else:
                self.allocator.free_sequence(seq.alloc)
            seq.alloc = None
        seq.emit(Annotated.from_data(LLMEngineOutput.final(reason).to_dict(), id=seq.ctx.id))
        seq.emit(_FINISHED)

    def _watchdog_trip(self, seq: _Seq, defer_free: bool = False) -> None:
        """Output watchdog (docs/resilience.md §Silent corruption): the
        lane's dispatch produced non-finite or exploding logits. The lane
        dies HERE, typed and in-band — the PR10 contract (never raise past
        delivered tokens) means the stream ends with an explicit resume
        directive: a journaled client re-admits on a sibling and the
        caller sees an unbroken, byte-correct stream; a journal-less
        client gets an explicit in-band error, never silent garbage.
        Nothing from the tripped dispatch is emitted or sealed (the KV it
        wrote is suspect too); the lane's UNSEALED tail blocks free with
        the allocation, its pre-trip sealed blocks were computed by
        healthy dispatches and stay cached. The trip counts against this
        worker's quarantine window. Engine thread only."""
        self.watchdog_trips += 1
        integrity_mod.note_trip("watchdog", where="engine")
        logger.error(
            "output watchdog tripped for request %s: non-finite or "
            "exploding logits — ending the stream with a resume directive",
            seq.ctx.id,
        )
        if tracing.enabled():
            self._record_phase_spans(seq, FinishReason.ERROR)
        if seq.slot is not None:
            self._slots[seq.slot] = None
            seq.slot = None
        if seq.alloc is not None:
            if defer_free:
                # the in-flight speculative chunk may still write into
                # these blocks; park them until it has been fetched
                self._zombie_allocs.append(seq.alloc)
            else:
                self.allocator.free_sequence(seq.alloc)
            seq.alloc = None
        seq.emit(Annotated.from_data(
            {"migrating": {
                "resume": True,
                "error": "output watchdog: non-finite or exploding logits",
            }},
            id=seq.ctx.id,
        ))
        seq.emit(_FINISHED)

    def _preempt(self, seq: _Seq) -> None:
        """Out of KV blocks mid-decode: recompute-preempt — free pages, requeue
        with prompt := prompt + generated, prefix cache softens the recompute.

        ``generated`` is cleared so positions/total_len stay consistent after
        re-admission (it had been double-counted before, writing KV at wrong
        slots with wrong RoPE); ``seq.emitted`` keeps the caller-visible token
        count for max_tokens."""
        logger.warning("preempting request %s (out of KV blocks)", seq.ctx.id)
        self.preemptions += 1
        if seq.slot is not None:
            self._slots[seq.slot] = None
            seq.slot = None
        self.allocator.free_sequence(seq.alloc)
        seq.prompt = seq.prompt + seq.generated
        seq.generated = []
        seq.alloc = None
        seq.prefill_pos = None  # re-set from the fresh allocation on re-admit
        with self._cond:
            self._pending.append(seq)

    # -- disaggregated prefill ------------------------------------------------

    def set_remote_prefill_policy(self, policy) -> None:
        """policy must provide should_remote(uncached_len)->bool and
        submit(request_id, token_ids, block_ids, cached_tokens, sampling)
        (called from the engine thread; submit must be thread-safe)."""
        self._remote_policy = policy

    def extract_blocks(self, block_ids: List[int], as_device: bool = False):
        """Copy KV pages out of the pool: ``(k, v, k_scale, v_scale)`` with
        pages [L, n, bs, KVH, D] ×2 and, for int8 pools, the per-token scale
        tables [L, n, bs] ×2 (None on native-dtype pools — scales travel
        WITH their pages through every transfer tier). Host numpy, or device
        arrays with ``as_device`` (same-host transfers keep pages on-device
        and let XLA reshard at the destination's inject boundary).
        MUST run on the engine thread (e.g. via post())."""
        idx = jnp.asarray(block_ids, jnp.int32)
        arrs = [self.cache["k"][:, idx], self.cache["v"][:, idx]]
        if self._kv_quantized:
            arrs.append(self.cache["k_scale"][:, idx])
            arrs.append(self.cache["v_scale"][:, idx])
        if as_device:
            out = list(arrs)
        else:
            for a in arrs:
                a.copy_to_host_async()
            # dynlint: allow-host-sync(page extraction for KV transfer; off
            # the decode loop, copies started async above)
            out = [np.asarray(x) for x in jax.device_get(arrs)]
        while len(out) < 4:
            out.append(None)
        return tuple(out)

    def block_hashes_of(self, block_ids: List[int]) -> List[int]:
        """The allocator-registered content hash per physical page (-1 for a
        page with no registered hash — free, partial, or reused). Lets a
        remote reader verify pages still hold the content it expects; MUST
        run on the engine thread."""
        return [self.allocator.hash_of_block(bid) for bid in block_ids]

    def block_crcs_of(self, block_ids: List[int]) -> List[int]:
        """Seal-time content checksums per physical page (-1 when unsealed
        or sealed before the integrity plane was on). Transfer tiers ship
        these next to the pages; a -1 entry means "sender can't vouch" and
        receivers fall back to extract-time (wire-only) checksums. MUST run
        on the engine thread."""
        return [self.allocator.crc_of_block(bid) for bid in block_ids]

    def _block_checksums(self, block_ids: List[int]) -> List[int]:
        """The allocator's seal-time checksum callback: pull the freshly
        sealed pages' bytes and crc them (runtime/integrity.py). This is
        the integrity plane's steady-state cost — one small device→host
        copy per sealed block, knob-gated by DYN_TPU_KV_INTEGRITY. MUST
        run on the engine thread (note_tokens_computed call sites)."""
        k, v, ks, vs = self.extract_blocks(block_ids)
        return integrity_mod.page_checksums(k, v, ks, vs)

    def seed_external_prefix(
        self, token_ids: List[int], k_pages, v_pages,
        k_scale=None, v_scale=None,
    ) -> int:
        """Register externally-computed prefix KV (pages read from another
        worker) into this engine's prefix cache: allocator registration +
        page injection, atomically on the engine thread. ``k_pages`` covers
        ALL full blocks of ``token_ids`` ([L, n_full, bs, KVH, D]); already-
        cached blocks are skipped. int8 pools require the matching per-token
        scale tables ([L, n_full, bs]). Returns the number of blocks seeded.
        MUST run on the engine thread (via post())."""
        if self._kv_quantized != (k_scale is not None):
            # check BEFORE touching the allocator: a mismatch must not leave
            # seeded-but-never-injected hashes in the prefix cache
            raise KvDtypeMismatch(
                "pool kv_dtype is %s but pages %s scale tables" % (
                    "int8" if self._kv_quantized else "native",
                    "lack" if k_scale is None else "carry",
                )
            )
        pairs = self.allocator.seed_cached(token_ids)
        if not pairs:
            return 0
        block_ids = [bid for _, bid in pairs]
        sel = [i for i, _ in pairs]
        if isinstance(k_pages, jax.Array):
            sel = jnp.asarray(sel, jnp.int32)
        self.inject_blocks(
            block_ids, k_pages[:, sel], v_pages[:, sel],
            k_scale[:, sel] if k_scale is not None else None,
            v_scale[:, sel] if v_scale is not None else None,
        )
        return len(pairs)

    # -- held allocations (prefill-worker page extraction) --------------------

    def hold_pages(self, request_id: str) -> None:
        """Mark a request's pages to be parked (not freed) when it finishes,
        so a caller can extract them afterwards. Thread-safe; call before
        submitting the request. Pair with :meth:`release_held`."""
        self._hold_ids.add(request_id)

    def take_held_pages(
        self, request_id: str, first_block: int, n_blocks: int,
        as_device: bool = False,
    ):
        """Extract pages [first_block, n_blocks) of a finished held request,
        then release its allocation. MUST run on the engine thread."""
        self._hold_ids.discard(request_id)
        alloc = self._held_allocs.pop(request_id, None)
        if alloc is None:
            raise KeyError(f"no held allocation for request {request_id}")
        try:
            ids = alloc.block_ids[first_block:n_blocks]
            return self.extract_blocks(ids, as_device=as_device)
        finally:
            self.allocator.free_sequence(alloc)

    def release_held(self, request_id: str) -> None:
        """Free a held allocation without extracting (error paths).
        MUST run on the engine thread."""
        self._hold_ids.discard(request_id)
        alloc = self._held_allocs.pop(request_id, None)
        if alloc is not None:
            self.allocator.free_sequence(alloc)

    # -- live in-flight migration (disagg/migration.py) -----------------------
    #
    # Source side: export_migratable freezes mid-decode sequences; the drain
    # coordinator extracts their pages, ships a `migrate` frame, and ends
    # each stream with an in-band marker (finish_migrated / abort_migration).
    # Target side: stage_migration adopts the pages into a pre-built
    # allocation whose cached_tokens covers every already-computed position
    # (0..N-2 of the N-token prompt+emitted history — position N-1 was never
    # computed anywhere: the source sampled its token but hadn't fed it yet).
    # The re-homed client's attach then rides the ORDINARY admission path for
    # a pre-held allocation: prefill_pos = N-1, one fresh position computed,
    # zero positions recomputed, greedy continuation bitwise identical.

    def export_migratable(self) -> List[dict]:
        """Freeze every migratable sequence (mid-decode, ≥1 generated token,
        not remote-awaiting/cancelled) out of its slot and return one
        checkpoint per stream. Frozen sequences stop decoding but keep
        their allocation until finish/abort/unfreeze. MUST run on the
        engine thread (via post())."""
        self._drain_inflight()  # commit speculative writes; host state final
        out: List[dict] = []
        bs = self.config.kv_block_size
        for i, seq in enumerate(self._slots):
            if (
                seq is None or seq.prefill_pos is not None
                or not seq.generated or seq.ctx.context.is_stopped
            ):
                continue
            self._slots[i] = None
            seq.slot = None
            self._migrating_out[seq.ctx.id] = seq
            toks = seq.prompt + seq.generated
            n_hist = len(toks) - 1
            out.append({
                "request_id": seq.ctx.id,
                "mid": uuid.uuid4().hex[:16],
                "token_ids": toks,
                # caller-visible output across ALL legs of this stream
                # (out_tokens carries resume/migrate-seeded history plus
                # everything emitted here) — the client validates its
                # journal against this; seq.emitted would under-count a
                # stream that already migrated once
                "emitted": len(seq.out_tokens),
                "tenant": seq.tenant,
                "level": seq.level,
                "n_blocks": (n_hist + bs - 1) // bs,
            })
        return out

    def extract_for_migration(self, request_id: str):
        """Copy a frozen sequence's computed-history pages out of the pool:
        blocks covering positions 0..N-2 (the last sampled token was never
        fed, so its position has no KV anywhere). Returns ``(k, v,
        k_scale, v_scale, crcs)`` — ``crcs`` is the per-block content
        checksum list the migrate frame ships (seal-time registry values
        where the block is sealed, extract-time values for the partial
        tail; None with the integrity plane off). MUST run on the engine
        thread."""
        seq = self._migrating_out[request_id]  # KeyError → coordinator aborts
        n_hist = len(seq.prompt) + len(seq.generated) - 1
        n_blocks = (n_hist + self.config.kv_block_size - 1) // self.config.kv_block_size
        bids = seq.alloc.block_ids[:n_blocks]
        k, v, ks, vs = self.extract_blocks(bids)
        crcs = None
        if self._integrity is not None:
            # seal-time checksums where the owner can vouch for the block
            # (catches HBM rot between seal and drain); the unsealed tail
            # gets extract-time checksums — wire-scope protection only
            crcs = self.block_crcs_of(bids)
            for i, c in enumerate(crcs):
                if c < 0:
                    crcs[i] = integrity_mod.entry_checksum(
                        k[:, i], v[:, i],
                        ks[:, i] if ks is not None else None,
                        vs[:, i] if vs is not None else None,
                    )
        return k, v, ks, vs, crcs

    def finish_migrated(self, request_id: str, target_instance: str,
                        target_worker: str, mid: str) -> None:
        """The target staged this stream: end it with the in-band re-home
        marker and free the local pages (their contents were copied out).
        MUST run on the engine thread."""
        seq = self._migrating_out.pop(request_id, None)
        if seq is None:
            return
        self.migrated_out_requests += 1
        seq.emit(Annotated.from_data(
            {"migrating": {
                "instance": target_instance, "worker": target_worker,
                "mid": mid, "emitted": len(seq.out_tokens),
            }},
            id=seq.ctx.id,
        ))
        seq.emit(_FINISHED)
        if seq.alloc is not None:
            self.allocator.free_sequence(seq.alloc)
            seq.alloc = None

    def abort_migration(self, request_id: str, reason: str = "") -> None:
        """Migration of a frozen stream failed (transport, target nack, no
        target): end the stream with a resume directive — the client
        degrades to the ordinary resume path (re-admit anywhere, recompute
        softened by the prefix cache). MUST run on the engine thread."""
        seq = self._migrating_out.pop(request_id, None)
        if seq is None:
            return
        self.migrations_failed += 1
        seq.emit(Annotated.from_data(
            {"migrating": {"resume": True, "error": reason}}, id=seq.ctx.id,
        ))
        seq.emit(_FINISHED)
        if seq.alloc is not None:
            self.allocator.free_sequence(seq.alloc)
            seq.alloc = None

    def unfreeze_migrations(self) -> int:
        """Undrained before shipping: frozen sequences re-enter the pending
        queue with allocation and generated history intact — the decode-
        ready re-admission path puts them back in a slot exactly where they
        stopped. MUST run on the engine thread."""
        n = 0
        with self._cond:
            for seq in self._migrating_out.values():
                self._pending.append(seq)
                n += 1
            self._migrating_out.clear()
            if n:
                self._cond.notify()
        return n

    def cut_for_resume(self) -> int:
        """Drain-deadline force-cut: every remaining live stream (slots,
        pending, remote-awaiting, still-frozen) ends with a resume
        directive so the process can exit; clients re-admit elsewhere.
        MUST run on the engine thread."""
        self._drain_inflight()
        cut: List[_Seq] = []
        for i, seq in enumerate(self._slots):
            if seq is not None:
                self._slots[i] = None
                seq.slot = None
                cut.append(seq)
        with self._cond:
            cut.extend(self._pending)
            self._pending.clear()
        cut.extend(self._awaiting.values())
        self._awaiting.clear()
        cut.extend(self._migrating_out.values())
        self._migrating_out.clear()
        for seq in cut:
            seq.emit(Annotated.from_data(
                {"migrating": {"resume": True, "error": "drain deadline"}},
                id=seq.ctx.id,
            ))
            seq.emit(_FINISHED)
            if seq.alloc is not None:
                self.allocator.free_sequence(seq.alloc)
                seq.alloc = None
        return len(cut)

    def live_request_count(self) -> int:
        """Streams this engine still owes an ending (thread-safe)."""
        with self._cond:
            return (
                sum(1 for s in self._slots if s is not None)
                + len(self._pending) + len(self._awaiting)
                + len(self._migrating_out)
            )

    def _migration_ttl(self) -> float:
        ttl = getattr(self, "_staged_ttl", None)
        if ttl is None:
            from dynamo_tpu.disagg.migration import MigrationPolicy

            ttl = self._staged_ttl = MigrationPolicy.from_env().staged_ttl
        return ttl

    def stage_migration(self, meta: dict, k_np, v_np, k_scale=None,
                        v_scale=None) -> dict:
        """Target side: adopt a migrating stream's KV pages ahead of its
        client's re-homed admission. Validates layout, allocates for the
        full N-token history, injects the wire pages over everything the
        local prefix cache doesn't already cover, seals the computed blocks
        into the prefix cache (they are ordinary cluster-visible prefix
        hits from here on), and parks the allocation keyed by migration id
        with ``cached_tokens = N-1`` — the attach then computes exactly one
        fresh position. Any rejection raises BEFORE pool state changes
        beyond a rolled-back allocation: never a torn page set. MUST run on
        the engine thread."""
        toks = [int(t) for t in meta["token_ids"]]
        if len(toks) < 2:
            raise MigrationRejected("history too short to migrate")
        if len(toks) > self.config.max_model_len - 1:
            raise MigrationRejected(
                f"history is {len(toks)} tokens; engine max_model_len is "
                f"{self.config.max_model_len}"
            )
        bs = self.config.kv_block_size
        if self._kv_quantized != (k_scale is not None):
            raise KvDtypeMismatch(
                "pool kv_dtype is %s but migrated pages %s scale tables" % (
                    "int8" if self._kv_quantized else "native",
                    "lack" if k_scale is None else "carry",
                )
            )
        if k_np.shape[2] != bs:
            raise MigrationRejected(
                f"migrated pages have block_size {k_np.shape[2]}, engine "
                f"uses {bs}"
            )
        n_hist = len(toks) - 1
        n_blocks = (n_hist + bs - 1) // bs
        if k_np.shape[1] != n_blocks:
            raise MigrationRejected(
                f"page set covers {k_np.shape[1]} blocks, history needs "
                f"{n_blocks}"
            )
        tenant = str(meta.get("tenant") or "")
        level = int(meta.get("level") or 0)
        mid = str(meta["mid"])  # parse BEFORE allocating: a malformed
        # checkpoint must not cost pool state
        if self._integrity is not None and meta.get("crcs") is not None:
            # content verification BEFORE any pool state changes: a page
            # set corrupted after the source sealed it (bad HBM there, bad
            # wire hop) raises typed — the nack degrades the stream to the
            # resume path and the SOURCE counts the trip against itself.
            # Never a torn staged entry: nothing was allocated yet.
            integrity_mod.verify_pages(
                k_np, v_np,
                (k_scale, v_scale) if k_scale is not None else None,
                meta["crcs"], where="migrate_stage",
            )
        alloc = self.allocator.allocate_sequence(
            toks, wait_inflight=False, tenant=tenant, level=level
        )
        if alloc is None:
            raise MigrationRejected("target out of KV blocks")
        try:
            # local device hits cover the leading cached_tokens//bs blocks;
            # the wire pages fill everything after them. Host-tier hits are
            # dropped: their blocks are freshly-taken single-owner pages the
            # wire content (same tokens, the source's ground-truth KV)
            # overwrites anyway.
            n_dev = alloc.cached_tokens // bs - len(alloc.host_hits)
            alloc.host_hits = []
            if n_dev < n_blocks:
                self.inject_blocks(
                    alloc.block_ids[n_dev:n_blocks],
                    k_np[:, n_dev:n_blocks], v_np[:, n_dev:n_blocks],
                    k_scale[:, n_dev:n_blocks]
                    if k_scale is not None else None,
                    v_scale[:, n_dev:n_blocks]
                    if v_scale is not None else None,
                )
            # seal the computed history: full blocks register in the prefix
            # cache — the migrated prefix is now a cluster-adopted cache
            # entry other requests can hit (ROADMAP item 3's "move the KV"
            # pipe)
            self.allocator.note_tokens_computed(
                alloc, toks[alloc.cached_tokens:n_hist]
            )
        except BaseException:
            # injection/sealing failed past the shape checks (e.g. KV
            # geometry skew the scatter rejects): the nack must not leak
            # the allocation — every drain retry would otherwise bleed the
            # target's pool dry
            self.allocator.free_sequence(alloc)
            raise
        alloc.cached_tokens = n_hist
        self._staged_migrations[mid] = (
            alloc, tuple(toks), time.perf_counter() + self._migration_ttl(),
        )
        with self._cond:
            self._cond.notify()  # wake the idle park so the TTL sweep runs
        return {"mid": mid, "blocks": n_blocks, "cached_tokens": n_hist}

    def _adopt_staged(self, seq: "_Seq") -> None:
        """Admission-time attach: a request carrying a migrate id adopts its
        staged allocation (cached_tokens = N-1 ⇒ prefill computes exactly
        one fresh position). Token mismatch or a missing/expired stage
        falls through to the ordinary resume recompute — the stage-seeded
        blocks still serve as plain prefix hits. Engine thread only."""
        mid = str(seq.request.migrate)
        entry = self._staged_migrations.pop(mid, None)
        if entry is None:
            return
        alloc, toks, _deadline = entry
        if list(toks) != seq.prompt:
            # the client's journal and the source's checkpoint disagree
            # (undelivered tokens at cut time): the staged KV covers a
            # different history — recompute path, blocks back to the cache
            self.allocator.free_sequence(alloc)
            return
        if alloc.tenant != seq.tenant or alloc.level != seq.level:
            self.allocator.retag_sequence(alloc, seq.tenant, seq.level)
        seq.alloc = alloc
        seq.migrated = True
        self.migrated_in_requests += 1

    def _sweep_staged(self) -> None:
        """Free staged migrations whose client never attached (engine
        thread, every loop pass; dict-empty check is the only steady-state
        cost)."""
        if not self._staged_migrations:
            return
        now = time.perf_counter()
        for mid, (alloc, _toks, deadline) in list(
            self._staged_migrations.items()
        ):
            if now > deadline:
                del self._staged_migrations[mid]
                n_blocks = len(alloc.block_ids)
                self.allocator.free_sequence(alloc)
                logger.warning(
                    "staged migration %s expired unclaimed; freed %d blocks",
                    mid, n_blocks,
                )

    def _inject_fn(self):
        if not hasattr(self, "_inject_jit"):
            record_compile("inject")

            def inject(cache_arr, idx, vals):
                # padded idx entries are out of range → dropped by the scatter
                return cache_arr.at[:, idx].set(vals, mode="drop")

            self._inject_jit = jax.jit(inject, donate_argnums=(0,))
        return self._inject_jit

    def inject_blocks(
        self, block_ids: List[int], k_np, v_np, k_scale=None, v_scale=None
    ) -> None:
        """Write transferred KV pages into HBM at the given physical pages.
        MUST run on the engine thread. Donated update (no cache-sized copy);
        the page count is padded to a power of two so at most log2(max_blocks)
        shapes ever compile — an unpadded count would recompile the donated
        scatter (and stall decode) for every distinct transfer size.

        Accepts host numpy (staged transfers) or jax arrays (the same-host
        device path: pages flow device→device, resharding across meshes —
        including differing tp — handled by XLA at the jit boundary).

        int8 pools require matching per-token scale tables ([L, n, bs] ×2);
        a layout mismatch raises :class:`KvDtypeMismatch` before any byte
        lands — corrupt pages are strictly worse than a failed transfer."""
        if self._kv_quantized != (k_scale is not None):
            raise KvDtypeMismatch(
                "pool kv_dtype is %s but injected pages %s scale tables" % (
                    "int8" if self._kv_quantized else "native",
                    "lack" if k_scale is None else "carry",
                )
            )
        n = len(block_ids)
        bucket = 1
        while bucket < n:
            bucket *= 2
        idx = np.full((bucket,), self.num_blocks, np.int32)  # out-of-range pad
        idx[:n] = block_ids
        dt = self.cache["k"].dtype

        def pad(vals):
            if isinstance(vals, jax.Array):
                widths = [(0, 0), (0, bucket - n)] + [(0, 0)] * (vals.ndim - 2)
                out = jnp.pad(vals, widths)
                # commit onto THIS engine's devices: jax.device_put reshards
                # across meshes, but jit's device check rejects an input
                # committed to a different mesh (split-chip prefill/decode)
                if self.mesh is not None:
                    from dynamo_tpu.parallel.mesh import kv_cache_sharding

                    return jax.device_put(out, kv_cache_sharding(self.mesh))
                return jax.device_put(out, next(iter(self.cache["k"].devices())))
            out = np.zeros((vals.shape[0], bucket) + vals.shape[2:], vals.dtype)
            out[:, :n] = vals
            return out

        fn = self._inject_fn()
        idx_dev = jnp.asarray(idx)
        self.cache["k"] = fn(self.cache["k"], idx_dev, jnp.asarray(pad(k_np), dt))
        self.cache["v"] = fn(self.cache["v"], idx_dev, jnp.asarray(pad(v_np), dt))
        if k_scale is not None:
            # scale tables ride the same padded scatter ([L, n, bs] slots in
            # place of [L, n, bs, KVH, D] pages — pad() is rank-agnostic)
            sdt = self.cache["k_scale"].dtype
            self.cache["k_scale"] = fn(
                self.cache["k_scale"], idx_dev, jnp.asarray(pad(k_scale), sdt)
            )
            self.cache["v_scale"] = fn(
                self.cache["v_scale"], idx_dev, jnp.asarray(pad(v_scale), sdt)
            )

    # -- host KV tier ---------------------------------------------------------

    def _offload_blocks(self, pairs: List[Tuple[int, int, Any]]) -> None:
        """Spill evicted device blocks to the host pool — WITHOUT stalling the
        eviction path (which runs inside admission: a synchronous device_get
        here stalls every decode lane for a host-transfer round trip, W4 of
        the round-2 review; the reference overlaps tier copies with its
        CopyStream, lib/llm/src/kv/layer.rs:100-1132).

        Engine thread only. The gather into fresh device buffers is enqueued
        BEFORE any subsequent dispatch that could overwrite the freed pages
        (single device stream executes in order), so the snapshot is
        consistent; the host copy then rides along asynchronously and is
        harvested by :meth:`_harvest_spills` once ready. ``pairs`` entries
        are ``(hash, block_id, crc)`` — the seal-time content checksum
        rides into the host tier with its block (None with integrity off)."""
        idx = jnp.asarray([bid for _, bid, _ in pairs], jnp.int32)
        k = self.cache["k"][:, idx]
        v = self.cache["v"][:, idx]
        k.copy_to_host_async()
        v.copy_to_host_async()
        ks = vs = None
        if self._kv_quantized:
            ks = self.cache["k_scale"][:, idx]
            vs = self.cache["v_scale"][:, idx]
            ks.copy_to_host_async()
            vs.copy_to_host_async()
        self._pending_spills.append((pairs, k, v, ks, vs))

    def _harvest_spills(self, force: bool = False) -> None:
        """Move completed async spills into the host pool (engine thread).
        Non-blocking by default (only entries whose copies are ready);
        ``force`` drains everything (close/idle). A deep backlog is force-
        drained so pending device snapshots can't pile up unboundedly."""
        if not self._pending_spills:
            return
        if len(self._pending_spills) > 8:
            force = True
        while self._pending_spills:
            pairs, k, v, ks, vs = self._pending_spills[0]
            if not force:
                try:
                    if not (k.is_ready() and v.is_ready()):
                        return
                except AttributeError:  # backend without is_ready: block
                    pass
            self._pending_spills.popleft()
            # dynlint: allow-host-sync(host-tier spill harvest: only taken
            # once is_ready(), or force-drained while the engine is idle)
            k_np = np.asarray(jax.device_get(k))
            v_np = np.asarray(jax.device_get(v))  # dynlint: allow-host-sync(ditto)
            if ks is not None:
                # dynlint: allow-host-sync(scale tables ride the same spill)
                ks_np = np.asarray(jax.device_get(ks))
                vs_np = np.asarray(jax.device_get(vs))  # dynlint: allow-host-sync(ditto)
            if faults_mod.current() is not None:
                # host-tier leg of the silent-corruption drill: the
                # "corrupt" action bit-flips the spilled copy — bad host
                # RAM; the seal-time crc must catch it at rehit
                k_np = faults_mod.corrupt_array(
                    "engine", self._fault_addr, k_np
                )
            for i, (h, _, crc) in enumerate(pairs):
                # copies, not views: a view would pin the whole batch array
                # in host RAM for as long as any one entry stays in the pool
                self.host_pool.put(
                    h,
                    np.ascontiguousarray(k_np[:, i]),
                    np.ascontiguousarray(v_np[:, i]),
                    np.ascontiguousarray(ks_np[:, i]) if ks is not None else None,
                    np.ascontiguousarray(vs_np[:, i]) if ks is not None else None,
                    crc=crc,
                )

    def _inject_host_hits(self, alloc: SequenceAllocation) -> None:
        """Load host-tier prefix hits back into the sequence's device pages
        (engine thread only). Runs before any compute touches the sequence.
        int8 pools carry their per-token scale tables through the same hop
        (allocator host_hits 6-tuples)."""
        hits = alloc.host_hits
        block_ids = [alloc.block_ids[h[0]] for h in hits]
        k = np.stack([h[2] for h in hits], axis=1)
        v = np.stack([h[3] for h in hits], axis=1)
        ks = vs = None
        if hits[0][4] is not None:
            ks = np.stack([h[4] for h in hits], axis=1)
            vs = np.stack([h[5] for h in hits], axis=1)
        alloc.host_hits = []
        self.inject_blocks(block_ids, k, v, ks, vs)

    def complete_remote_prefill(
        self, request_id: str, first_token: int, block_ids: List[int],
        k_np, v_np, k_scale=None, v_scale=None,
    ) -> None:
        """Called (any thread) when a prefill worker's KV lands for a waiting
        sequence: injects pages, registers the prompt KV, emits the first
        token, and queues the sequence for a decode slot. int8 pools expect
        the per-token scale tables; a layout mismatch (peer without dtype
        support, or a native peer shipping into an int8 pool) falls the
        request back to local prefill instead of writing corrupt pages."""

        def apply():
            seq = self._awaiting.pop(request_id, None)
            if seq is None:
                logger.warning("remote prefill for unknown request %s", request_id)
                return
            # inject only the pages the prefill worker computed (suffix after
            # any prefix-cache hit)
            if block_ids:
                bs = self.config.kv_block_size
                if k_np.shape[2] != bs:
                    logger.error(
                        "remote prefill for %s has block_size %d, engine uses %d"
                        " — falling back to local prefill",
                        request_id, k_np.shape[2], bs,
                    )
                    self._awaiting[request_id] = seq
                    self.fail_remote_prefill(request_id, "block_size mismatch")
                    return
                try:
                    self.inject_blocks(block_ids, k_np, v_np, k_scale, v_scale)
                except KvDtypeMismatch as e:
                    logger.error(
                        "remote prefill for %s: %s — falling back to local "
                        "prefill", request_id, e,
                    )
                    self._awaiting[request_id] = seq
                    self.fail_remote_prefill(request_id, f"kv_dtype mismatch: {e}")
                    return
            self.allocator.note_tokens_computed(seq.alloc, seq.prompt[seq.alloc.cached_tokens:])
            seq.first_token_t = time.perf_counter()
            self._emit_token(seq, int(first_token))
            if seq.alloc is not None:  # not finished by the first token
                with self._cond:
                    self._pending.append(seq)
                    self._cond.notify()

        self.post(apply)

    def fail_remote_prefill(self, request_id: str, message: str) -> None:
        """Remote prefill failed: fall back to computing the prefill locally
        (the allocation is still held; seq.remote stays True so _admit won't
        re-dispatch it)."""

        def apply():
            seq = self._awaiting.pop(request_id, None)
            if seq is None:
                return
            logger.warning(
                "remote prefill failed for %s (%s): falling back to local",
                request_id, message,
            )
            with self._cond:
                self._pending.append(seq)
                self._cond.notify()

        self.post(apply)

    def _sweep_remote_timeouts(self) -> None:
        if not self._awaiting:
            return
        now = time.perf_counter()
        for rid, seq in list(self._awaiting.items()):
            if seq.remote_deadline is not None and now > seq.remote_deadline:
                del self._awaiting[rid]
                logger.warning(
                    "remote prefill for %s timed out after %.0fs: prefilling locally",
                    rid, self.config.remote_prefill_timeout,
                )
                with self._cond:
                    self._pending.append(seq)

    def set_event_sink(self, sink: KvEventSink) -> None:
        """Attach/replace the KV event sink (e.g. the distributed publish
        bridge) after construction."""
        self.allocator.set_sink(sink)

    # -- metrics -------------------------------------------------------------

    def metrics_snapshot(self) -> Dict[str, Any]:
        """ForwardPassMetrics-equivalent (reference kv_router/protocols.rs:42-54).

        Taken under the engine condition lock so slot/allocator counters are
        mutually consistent (they feed the KV scheduler's cost function)."""
        with self._cond:
            return self._metrics_locked()

    def _metrics_locked(self) -> Dict[str, Any]:
        active = sum(1 for s in self._slots if s is not None)
        probe = max(self.allocator.probe_tokens, 1)
        m = {
            "request_active_slots": active,
            "request_total_slots": self.config.max_slots,
            "kv_active_blocks": self.allocator.active_blocks,
            "kv_total_blocks": self.num_blocks,
            # direct admission signals (runtime/admission.py gates on free
            # KV headroom; reclaimable = the warm-cache share of it)
            "kv_free_blocks": self.allocator.free_blocks,
            "kv_reclaimable_blocks": self.allocator.reclaimable_blocks,
            "num_requests_waiting": len(self._pending) + len(self._awaiting),
            "gpu_cache_usage_perc": self.allocator.usage(),
            "gpu_prefix_cache_hit_rate": self.allocator.hit_tokens / probe,
            # shared in-flight prefill registry (reserved.rs parity):
            # deferrals onto a concurrent identical prefix + tokens saved
            "inflight_prefill_waits": self.allocator.inflight_waits,
            "shared_prefill_tokens": self.allocator.shared_prefill_tokens,
            # live perf accounting (telemetry plane): the BENCH roofline
            # inputs as gauges; zeros with sampling off (DYN_TPU_SLO=0)
            "jit_recompiles": compile_count(),
            "kv_peak_occupancy_perc": round(self.allocator.peak_occupancy(), 4),
            # speculative decoding + KV layout (PR7): cumulative draft
            # counters are host-side truth (live with or without telemetry);
            # the EMA acceptance gauge needs perf sampling
            "spec_drafted_tokens": self.spec_drafted_total,
            "spec_accepted_tokens": self.spec_accepted_total,
            "kv_quantized": int(self._kv_quantized),
            # mid-stream resume: re-admissions this engine served (the
            # client-side resume counters live in runtime/resilience.py)
            "resumed_requests": self.resumed_requests,
            # live migration (docs/resilience.md §Live migration): streams
            # shipped out on drain, staged imports a re-homed client
            # adopted, stages currently parked, and — the chaos-gate
            # observable — positions resumed admissions had to recompute
            # (migrated admissions add 0)
            "migrated_out_requests": self.migrated_out_requests,
            "migrated_in_requests": self.migrated_in_requests,
            "migrate_staged": len(self._staged_migrations),
            "resume_recompute_tokens": self.resume_recompute_tokens,
            # integrity plane (docs/resilience.md §Silent corruption):
            # engine-local watchdog trips (the process-global trip/
            # quarantine counters ride attach_kv_publishing)
            "watchdog_trips": self.watchdog_trips,
        }
        if self._perf is not None:
            m["decode_tokens_per_s"] = round(self._perf.decode_tps, 3)
            m["step_time_ms"] = round(self._perf.step_time_ms, 3)
            m["batch_slot_util"] = round(self._perf.slot_util, 4)
            m["spec_accept_rate"] = round(self._perf.spec_accept_rate, 4)
        if self._timeline is not None:
            # performance attribution plane (docs/observability.md
            # §Profiling): decode-phase device/host p95 split + device idle
            # fraction, from the process-global dispatch timeline
            m.update(self._timeline.gauges())
        if self._straggler is not None:
            # fail-slow plane (docs/resilience.md §Fail-slow): normalized
            # per-token latency + sample freshness for the aggregator's
            # differential verdict, and this worker's own latched verdict
            # echoed back so the cluster rollup counts suspects from the
            # same stream it ingests
            m.update(self._straggler.gauges())
            m["straggler_state"] = straggler_mod.verdict()
        if self.host_pool is not None:
            m["host_cache_blocks"] = len(self.host_pool)
            m["host_cache_hits"] = self.host_pool.hits
        if self._prefill_budget > 0 or self._fair is not None:
            # chunked-prefill interleaving bound (docs/qos.md): the
            # observable proving the duty cycle works — exported in the
            # single-tenant budget-only mode too
            m["prefill_interleave_max"] = self.prefill_interleave_max
        if self._fair is not None:
            # per-tenant occupancy: what llmctl tenant status and the
            # dynamo_tenant_* cluster gauges render
            tenants: Dict[str, Dict[str, Any]] = {}

            def entry(t: str) -> Dict[str, Any]:
                e = tenants.get(t)
                if e is None:
                    e = tenants[t] = {
                        "class": self._qos.class_name_of(t),
                        "active_slots": 0, "queue_depth": 0, "kv_blocks": 0,
                    }
                return e

            for s in self._slots:
                if s is not None and s.tenant:
                    entry(s.tenant)["active_slots"] += 1
            for s in list(self._pending) + list(self._awaiting.values()):
                if s.tenant:
                    entry(s.tenant)["queue_depth"] += 1
            # .copy(): one atomic C-level op — the engine thread mutates
            # this dict without holding _cond, so iterating it live from
            # the metrics/admission threads could see it resize mid-walk
            for t, n in self.allocator.tenant_blocks.copy().items():
                entry(t)["kv_blocks"] = n
            if tenants:
                m["tenants"] = tenants
        return m


def build_jax_serving_engine(
    card,
    max_batch_size: int = 8,
    kv_block_size: int = 16,
    max_model_len: Optional[int] = None,
    tensor_parallel_size: int = 1,
    num_kv_blocks: Optional[int] = None,
    seed: int = 0,
    event_sink: Optional[KvEventSink] = None,
    decode_steps: int = 4,
    host_cache_blocks: int = 0,
    pipeline_parallel_size: int = 1,
    context_parallel_size: int = 1,
    data_parallel_size: int = 1,
) -> JaxServingEngine:
    """CLI/SDK entry: model + engine from a ModelDeploymentCard."""
    from dynamo_tpu.engine_jax.weights import config_from_card, load_params
    from dynamo_tpu.models.llama import param_shardings
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh

    model_config = config_from_card(card)
    params = load_params(card, model_config, seed=seed)

    mesh = None
    mesh_cfg = MeshConfig(
        dp=data_parallel_size, pp=pipeline_parallel_size,
        tp=tensor_parallel_size, sp=context_parallel_size,
    )
    if mesh_cfg.size > 1:
        mesh = make_mesh(mesh_cfg)
        if jax.process_count() > 1:
            # process-spanning mesh: every host loaded the same full params;
            # each materializes only its device shards
            from dynamo_tpu.parallel.multihost_serving import shard_params_global

            params = shard_params_global(params, model_config, mesh)
        else:
            params = jax.device_put(params, param_shardings(model_config, mesh))

    engine_config = EngineConfig(
        max_slots=max_batch_size,
        kv_block_size=kv_block_size,
        max_model_len=max_model_len or min(card.context_length, 4096),
        num_kv_blocks=num_kv_blocks,
        decode_steps=decode_steps,
        host_cache_blocks=host_cache_blocks,
    )
    return JaxServingEngine(
        model_config, params, engine_config, mesh=mesh, event_sink=event_sink
    )
