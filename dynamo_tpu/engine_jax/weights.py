"""Model config resolution and weight loading for the JAX engine.

Maps an HF-layout model directory (config.json + *.safetensors) onto the
framework's stacked-layer parameter pytree (models/llama.py). Directories
without weight files get deterministic random init — enough for echo-free
serving-path tests and synthetic benchmarks.

Reference analogue: model resolution in launch/dynamo-run (hub.rs,
model_card/create.rs:41-143); actual weight loading lives in the delegated
engines there — here it is framework-native.
"""

from __future__ import annotations

import glob
import logging
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.models.llama import LlamaConfig, init_params

logger = logging.getLogger(__name__)


def config_from_card(card: ModelDeploymentCard, dtype: Any = jnp.bfloat16) -> LlamaConfig:
    """Derive a LlamaConfig from the card's HF config.json contents."""
    mc = card.model_config or {}
    hidden = int(mc.get("hidden_size", 4096))
    heads = int(mc.get("num_attention_heads", 32))
    return LlamaConfig(
        vocab_size=int(mc.get("vocab_size", 128256)),
        hidden_size=hidden,
        intermediate_size=int(mc.get("intermediate_size", 4 * hidden)),
        num_layers=int(mc.get("num_hidden_layers", 32)),
        num_heads=heads,
        num_kv_heads=int(mc.get("num_key_value_heads", heads)),
        head_dim=int(mc.get("head_dim", hidden // heads)),
        rope_theta=float(mc.get("rope_theta", 500000.0)),
        rms_norm_eps=float(mc.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(mc.get("tie_word_embeddings", False)),
        # qwen2 attention carries q/k/v biases (HF config doesn't flag it;
        # the architecture implies it)
        qkv_bias=mc.get("model_type") == "qwen2",
        # mixtral family: sparse MoE MLP, experts over the ep mesh axis
        num_experts=int(mc.get("num_local_experts", 0)),
        num_experts_per_tok=int(mc.get("num_experts_per_tok", 2)),
        dtype=dtype,
    )


def _hf_tensors(model_path: str) -> Optional[Dict[str, np.ndarray]]:
    files = sorted(glob.glob(os.path.join(model_path, "*.safetensors")))
    if not files:
        return None
    from safetensors import safe_open

    out: Dict[str, np.ndarray] = {}
    for f in files:
        with safe_open(f, framework="np") as sf:
            for name in sf.keys():
                out[name] = sf.get_tensor(name)
    return out


def load_params(card: ModelDeploymentCard, config: LlamaConfig, seed: int = 0):
    """Load llama weights (safetensors or GGUF) into the stacked pytree,
    or random-init when the card has no weight artifacts."""
    if card.gguf_path:
        from dynamo_tpu.llm.gguf import gguf_params, read_gguf

        return gguf_params(read_gguf(card.gguf_path), config)
    tensors = _hf_tensors(card.model_path) if card.model_path else None
    if tensors is None:
        logger.info("no safetensors found for %s: random-initializing", card.display_name)
        return init_params(jax.random.PRNGKey(seed), config)
    return params_from_hf(tensors, config)


def _mlp_weights(tensors: Dict[str, np.ndarray], c: LlamaConfig) -> Dict[str, Any]:
    """Dense llama/qwen2 MLP or mixtral sparse-MoE expert weights, stacked
    [L, ...] (and [L, X, ...] over experts). HF mixtral names:
    block_sparse_moe.gate (router) + experts.M.{w1,w3,w2} = gate/up/down."""
    dt = c.dtype

    def lin(name: str) -> np.ndarray:
        return np.ascontiguousarray(tensors[name].T)

    if c.num_experts > 1:
        def experts(fmt: str) -> jnp.ndarray:
            return jnp.asarray(
                np.stack([
                    np.stack([
                        lin(fmt.format(i, x)) for x in range(c.num_experts)
                    ])
                    for i in range(c.num_layers)
                ]),
                dt,
            )

        return {
            "moe_router": jnp.asarray(
                np.stack([
                    lin(f"model.layers.{i}.block_sparse_moe.gate.weight")
                    for i in range(c.num_layers)
                ]),
                jnp.float32,
            ),
            "w_gate": experts("model.layers.{}.block_sparse_moe.experts.{}.w1.weight"),
            "w_up": experts("model.layers.{}.block_sparse_moe.experts.{}.w3.weight"),
            "w_down": experts("model.layers.{}.block_sparse_moe.experts.{}.w2.weight"),
        }
    return {
        "w_gate": jnp.asarray(
            np.stack([lin(f"model.layers.{i}.mlp.gate_proj.weight") for i in range(c.num_layers)]), dt
        ),
        "w_up": jnp.asarray(
            np.stack([lin(f"model.layers.{i}.mlp.up_proj.weight") for i in range(c.num_layers)]), dt
        ),
        "w_down": jnp.asarray(
            np.stack([lin(f"model.layers.{i}.mlp.down_proj.weight") for i in range(c.num_layers)]), dt
        ),
    }


def params_from_hf(tensors: Dict[str, np.ndarray], config: LlamaConfig):
    """HF llama naming → framework pytree (transposed to [in, out] layout)."""
    c = config
    dt = c.dtype

    def get(name: str) -> np.ndarray:
        return tensors[name]

    def lin(name: str) -> np.ndarray:
        # HF nn.Linear stores [out, in]; we use [in, out]
        return np.ascontiguousarray(get(name).T)

    def stack(fmt: str, transform) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([transform(fmt.format(i)) for i in range(c.num_layers)]), dt
        )

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dt),
        "final_norm": jnp.asarray(get("model.norm.weight"), jnp.float32),
        "layers": {
            "attn_norm": jnp.asarray(
                np.stack([get(f"model.layers.{i}.input_layernorm.weight") for i in range(c.num_layers)]),
                jnp.float32,
            ),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight", lin),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight", lin),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight", lin),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", lin),
            **(
                {
                    "bq": jnp.asarray(np.stack(
                        [get(f"model.layers.{i}.self_attn.q_proj.bias") for i in range(c.num_layers)]
                    ), jnp.float32),
                    "bk": jnp.asarray(np.stack(
                        [get(f"model.layers.{i}.self_attn.k_proj.bias") for i in range(c.num_layers)]
                    ), jnp.float32),
                    "bv": jnp.asarray(np.stack(
                        [get(f"model.layers.{i}.self_attn.v_proj.bias") for i in range(c.num_layers)]
                    ), jnp.float32),
                }
                if c.qkv_bias
                else {}
            ),
            "mlp_norm": jnp.asarray(
                np.stack([get(f"model.layers.{i}.post_attention_layernorm.weight") for i in range(c.num_layers)]),
                jnp.float32,
            ),
            **_mlp_weights(tensors, c),
        },
    }
    if not c.tie_embeddings:
        params["lm_head"] = jnp.asarray(np.ascontiguousarray(get("lm_head.weight").T), dt)
    return params
