"""In-jit token sampling: greedy / temperature / top-k / top-p, per-slot.

Sampling runs inside the jitted step so only the sampled token ids (a few
bytes) cross the device→host boundary per step — never the [slots, vocab]
logits. All parameters are per-slot vectors so one compiled function serves
any mix of requests.

A full descending sort of a 128k vocab is one of the slowest single ops on
TPU (sorts don't map to the MXU); instead we take the top ``CANDIDATES``
logits with ``lax.top_k`` (a partial sort) and sample within them. top-k is
clamped to the candidate budget and top-p is computed over the renormalized
candidate mass — exact whenever the requested cutoff lies inside the top
candidates, which at serving temperatures it essentially always does.

Encoding of "disabled": temperature <= 0 → greedy; top_k <= 0 → no top-k;
top_p >= 1 → no top-p.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# static candidate budget for top-k/top-p; raising it trades step time for
# exactness of very flat sampling distributions. The preprocessor clamps
# requested top_k to this bound (with a warning) so the API never silently
# serves a different distribution than validated.
CANDIDATES = 64


def apply_penalties(
    logits: jax.Array,  # [B, V] float32
    counts: jax.Array,  # [B, V] int — output-token occurrence counts
    frequency_penalty: jax.Array,  # [B]
    presence_penalty: jax.Array,  # [B]
) -> jax.Array:
    """OpenAI-semantics repetition penalties over *output* token counts.

    ``logit[t] -= freq * count[t] + presence * (count[t] > 0)`` — the counts
    buffer is maintained in-jit by the engine's step functions (one
    scatter-add per sampled token), so penalties cost two [B, V] elementwise
    ops and never leave the device. Reference: penalties flow through
    SamplingOptions (lib/llm/src/protocols/common.rs:52-644).
    """
    cf = counts.astype(jnp.float32)
    return (
        logits
        - frequency_penalty[:, None] * cf
        - presence_penalty[:, None] * (cf > 0.0)
    )


def update_counts(
    counts: jax.Array,  # [B, V] int32
    tokens: jax.Array,  # [B] int32 sampled this step
    active: jax.Array,  # [B] bool — lanes whose sample is real (not padding)
) -> jax.Array:
    """Scatter-add this step's sampled tokens into the count buffer."""
    b = counts.shape[0]
    return counts.at[jnp.arange(b), tokens].add(active.astype(counts.dtype))


def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    keys: jax.Array,  # [B] PRNG keys (per-slot, honors per-request seeds)
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32
    top_p: jax.Array,  # [B]
    *,
    greedy_only: bool = False,
) -> jax.Array:
    """Sample one token per row. Returns [B] int32.

    ``greedy_only`` (static) compiles just the argmax: when no lane in the
    batch has temperature > 0, the top-k partial sort, softmax/cumsum and
    categorical draw are dead weight — several ms per decode step at a 128k
    vocab, paid every step of every dispatch. The engine picks the variant
    per dispatch from the live lanes' sampling options."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    if greedy_only:
        return greedy.astype(jnp.int32)

    c = min(CANDIDATES, v)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    top_logits, top_idx = jax.lax.top_k(scaled, c)  # [B, C], sorted desc

    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, c), c)[:, None]
    keep_k = ranks < k_eff

    probs_sorted = jax.nn.softmax(top_logits, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    keep_p = (cum - probs_sorted) < jnp.clip(top_p, 0.0, 1.0)[:, None]

    keep = keep_k & keep_p
    masked = jnp.where(keep, top_logits, -jnp.inf)
    choice = jax.vmap(jax.random.categorical)(keys, masked)  # [B] in [0, C)
    sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=1)[:, 0]

    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def speculative_targets(
    logits_all: jax.Array,  # [B, K1, V] f32 — one row per fed position
    counts: jax.Array,  # [B, V] int32 penalty counts (dummy when unused)
    active: jax.Array,  # [B, K1] bool — position actually fed (not padding)
    step_key: jax.Array,  # dispatch-level PRNG key
    seeds: jax.Array,  # [B] per-request seeds
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B]
    top_p: jax.Array,  # [B]
    frequency_penalty: jax.Array,  # [B]
    presence_penalty: jax.Array,  # [B]
    *,
    with_pen: bool,
    with_sample: bool,
    with_lp: bool,
    n_top: int = 0,
) -> tuple:
    """Target tokens for a speculative-verify dispatch, position by position.

    The verify step feeds ``[last_token, draft_0, .., draft_{k-1}]`` through
    one forward pass; this samples the engine's OWN next token at every fed
    position — exactly the token the sequential sampler would have produced
    given the same prefix and the same per-position key. The engine then
    keeps the drafted prefix that MATCHES these targets plus the first
    non-matching target as the bonus token. That acceptance rule is the
    rejection-sampling scheme specialized to point-mass (deterministic)
    proposals: every emitted token was drawn from the model's conditional at
    its position, so the emitted stream follows the exact autoregressive
    distribution — and greedy (temperature 0) output is bitwise identical to
    non-speculative greedy decode.

    Penalties are sequentially exact along the chunk: the scan carries the
    count buffer, adding each position's target before scoring the next —
    identical to one-token-at-a-time decoding for every position up to and
    including the first draft mismatch (positions past it are discarded by
    the engine, and their garbage-fed logits never leave the device as
    emitted tokens). Because rejected positions DO pollute the returned
    count buffer, the engine subtracts exactly the non-emitted targets from
    each penalized row after every verify dispatch (``_counts_fix_fn`` —
    O(spec_k) per lane, never a full out_tokens rebuild).

    Returns ``(targets [B, K1], counts)`` plus, with ``with_lp``,
    ``(chosen_lp [B, K1], top_ids [B, K1, n_top], top_lps [B, K1, n_top])``
    inserted before ``counts`` — mirroring the decode scan's layout.
    """
    k1 = logits_all.shape[1]

    def body(carry, j):
        cnt = carry
        sel = logits_all[:, j]
        if with_sample:
            kk = jax.random.fold_in(step_key, j)
            keys = jax.vmap(lambda s: jax.random.fold_in(kk, s))(seeds)
        else:
            keys = None
        sampled_from = (
            apply_penalties(sel, cnt, frequency_penalty, presence_penalty)
            if with_pen else sel
        )
        nxt = sample_tokens(sampled_from, keys, temperature, top_k, top_p,
                            greedy_only=not with_sample)
        if with_pen:
            cnt = update_counts(cnt, nxt, active[:, j])
        if with_lp:
            lp, tids, tlps = token_logprobs(sel, nxt, n_top)
            return cnt, (nxt, lp, tids, tlps)
        return cnt, nxt

    counts, out = jax.lax.scan(body, counts, jnp.arange(k1))
    # scan stacks position-major [K1, B, ...] → slot-major
    if with_lp:
        nxt, lp, tids, tlps = out
        return (
            nxt.T, lp.T, tids.transpose(1, 0, 2), tlps.transpose(1, 0, 2),
            counts,
        )
    return out.T, counts


def token_logprobs(
    logits: jax.Array,  # [B, V] float32 (raw, temperature-unscaled)
    tokens: jax.Array,  # [B] int32 sampled tokens
    n_top: int,
) -> tuple:
    """Model log-probabilities for OpenAI ``logprobs`` reporting.

    Returns (chosen_lp [B], top_ids [B, n_top], top_lps [B, n_top]); raw
    model distribution, not the sampling-modified one. n_top == 0 returns
    empty [B, 0] alternatives.
    """
    b, v = logits.shape
    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B]
    chosen = jnp.take_along_axis(logits, tokens[:, None], axis=1)[:, 0]
    chosen_lp = chosen - lse
    if n_top > 0:
        top_vals, top_ids = jax.lax.top_k(logits, n_top)
        top_lps = top_vals - lse[:, None]
    else:
        top_ids = jnp.zeros((b, 0), jnp.int32)
        top_lps = jnp.zeros((b, 0), jnp.float32)
    return chosen_lp, top_ids.astype(jnp.int32), top_lps
