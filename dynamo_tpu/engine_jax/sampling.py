"""In-jit token sampling: greedy / temperature / top-k / top-p, per-slot.

Sampling runs inside the jitted step so only the sampled token ids (a few
bytes) cross the device→host boundary per step — never the [slots, vocab]
logits. All parameters are per-slot vectors so one compiled function serves
any mix of requests.

A full descending sort of a 128k vocab is one of the slowest single ops on
TPU (sorts don't map to the MXU); instead we take the top ``CANDIDATES``
logits with ``lax.top_k`` (a partial sort) and sample within them. top-k is
clamped to the candidate budget and top-p is computed over the renormalized
candidate mass — exact whenever the requested cutoff lies inside the top
candidates, which at serving temperatures it essentially always does.

Encoding of "disabled": temperature <= 0 → greedy; top_k <= 0 → no top-k;
top_p >= 1 → no top-p.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# static candidate budget for top-k/top-p; raising it trades step time for
# exactness of very flat sampling distributions. The preprocessor clamps
# requested top_k to this bound (with a warning) so the API never silently
# serves a different distribution than validated.
CANDIDATES = 64


def apply_penalties(
    logits: jax.Array,  # [B, V] float32
    counts: jax.Array,  # [B, V] int — output-token occurrence counts
    frequency_penalty: jax.Array,  # [B]
    presence_penalty: jax.Array,  # [B]
) -> jax.Array:
    """OpenAI-semantics repetition penalties over *output* token counts.

    ``logit[t] -= freq * count[t] + presence * (count[t] > 0)`` — the counts
    buffer is maintained in-jit by the engine's step functions (one
    scatter-add per sampled token), so penalties cost two [B, V] elementwise
    ops and never leave the device. Reference: penalties flow through
    SamplingOptions (lib/llm/src/protocols/common.rs:52-644).
    """
    cf = counts.astype(jnp.float32)
    return (
        logits
        - frequency_penalty[:, None] * cf
        - presence_penalty[:, None] * (cf > 0.0)
    )


def update_counts(
    counts: jax.Array,  # [B, V] int32
    tokens: jax.Array,  # [B] int32 sampled this step
    active: jax.Array,  # [B] bool — lanes whose sample is real (not padding)
) -> jax.Array:
    """Scatter-add this step's sampled tokens into the count buffer."""
    b = counts.shape[0]
    return counts.at[jnp.arange(b), tokens].add(active.astype(counts.dtype))


def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    keys: jax.Array,  # [B] PRNG keys (per-slot, honors per-request seeds)
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32
    top_p: jax.Array,  # [B]
    *,
    greedy_only: bool = False,
) -> jax.Array:
    """Sample one token per row. Returns [B] int32.

    ``greedy_only`` (static) compiles just the argmax: when no lane in the
    batch has temperature > 0, the top-k partial sort, softmax/cumsum and
    categorical draw are dead weight — several ms per decode step at a 128k
    vocab, paid every step of every dispatch. The engine picks the variant
    per dispatch from the live lanes' sampling options."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    if greedy_only:
        return greedy.astype(jnp.int32)

    c = min(CANDIDATES, v)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    top_logits, top_idx = jax.lax.top_k(scaled, c)  # [B, C], sorted desc

    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, c), c)[:, None]
    keep_k = ranks < k_eff

    probs_sorted = jax.nn.softmax(top_logits, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    keep_p = (cum - probs_sorted) < jnp.clip(top_p, 0.0, 1.0)[:, None]

    keep = keep_k & keep_p
    masked = jnp.where(keep, top_logits, -jnp.inf)
    choice = jax.vmap(jax.random.categorical)(keys, masked)  # [B] in [0, C)
    sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=1)[:, 0]

    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def token_logprobs(
    logits: jax.Array,  # [B, V] float32 (raw, temperature-unscaled)
    tokens: jax.Array,  # [B] int32 sampled tokens
    n_top: int,
) -> tuple:
    """Model log-probabilities for OpenAI ``logprobs`` reporting.

    Returns (chosen_lp [B], top_ids [B, n_top], top_lps [B, n_top]); raw
    model distribution, not the sampling-modified one. n_top == 0 returns
    empty [B, 0] alternatives.
    """
    b, v = logits.shape
    lse = jax.scipy.special.logsumexp(logits, axis=-1)  # [B]
    chosen = jnp.take_along_axis(logits, tokens[:, None], axis=1)[:, 0]
    chosen_lp = chosen - lse
    if n_top > 0:
        top_vals, top_ids = jax.lax.top_k(logits, n_top)
        top_lps = top_vals - lse[:, None]
    else:
        top_ids = jnp.zeros((b, 0), jnp.int32)
        top_lps = jnp.zeros((b, 0), jnp.float32)
    return chosen_lp, top_ids.astype(jnp.int32), top_lps
