"""In-jit token sampling: greedy / temperature / top-k / top-p, per-slot.

Sampling runs inside the jitted step so only the sampled token ids (a few
bytes) cross the device→host boundary per step — never the [slots, vocab]
logits. All parameters are per-slot vectors so one compiled function serves
any mix of requests.

Encoding of "disabled": temperature <= 0 → greedy; top_k <= 0 → no top-k;
top_p >= 1 → no top-p.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    keys: jax.Array,  # [B] PRNG keys (per-slot, honors per-request seeds)
    temperature: jax.Array,  # [B]
    top_k: jax.Array,  # [B] int32
    top_p: jax.Array,  # [B]
) -> jax.Array:
    """Sample one token per row. Returns [B] int32."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # sort once (desc); both top-k and top-p masks derive from the sorted view
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]
    sorted_logits = jnp.take_along_axis(scaled, order, axis=-1)

    ranks = jnp.arange(v)[None, :]
    k_eff = jnp.where(top_k > 0, top_k, v)[:, None]
    keep_k = ranks < k_eff

    probs_sorted = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    keep_p = (cum - probs_sorted) < jnp.clip(top_p, 0.0, 1.0)[:, None]

    keep = keep_k & keep_p
    masked_sorted = jnp.where(keep, sorted_logits, -jnp.inf)
    choice_in_sorted = jax.vmap(jax.random.categorical)(keys, masked_sorted)  # [B]
    sampled = jnp.take_along_axis(order, choice_in_sorted[:, None], axis=1)[:, 0]

    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
